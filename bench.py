"""Headline benchmark: Sintel image-pairs/sec/chip @ iters=12.

Runs the flagship canonical RAFT-large forward (test_mode) at Sintel
resolution (436x1024 padded to 440x1024, the ``InputPadder`` pad-to-/8
shape) on the available accelerator and prints ONE JSON line. The
headline value is the eval-default correlation engine (round 4 flip:
the fused on-demand banded kernel — the reference's own sanctioned
``--alternate_corr`` eval mode, ``core/corr.py:64-92`` — wherever it
fits VMEM; identical parameters and golden-parity numerics), with the
materialized-volume arm always published alongside as
``value_all_pairs`` and promoted back to the headline if the banded arm
fails every band-mode rung. ``vs_baseline`` is measured against the
BASELINE.md north-star denominator: the PyTorch reference on 1xV100 at
the same setting, estimated at 10 image-pairs/sec (RAFT paper reports
~10 fps at 1088x436 / 12 iters on a 1080Ti-class GPU; BASELINE.md
records no in-repo number, so the target "≥4x vs V100" is normalized to
this documented estimate).

Throughput is measured at batch=24 (the sweep's knee on v5e-1; the f32
all-pairs volume pyramid for 24 pairs is ~6 GB of the 16 GB HBM): per-chip
eval throughput is the metric, and batching frame pairs is how the
framework evaluates a 1000-frame Sintel pass on TPU; reps are dispatched
back-to-back and synced once (via a scalar host readback — more reliable
than ``block_until_ready`` through the accelerator tunnel) so the device
pipeline rate is measured, not the host↔device round-trip latency of a
lone request.

Failure contract: this script ALWAYS prints exactly one JSON line.

Tunnel-outage strategy (round-3 redesign — two prior rounds lost the
driver artifact to backend-init hangs):

* **Probe ladder, not one long wait.** ``jax.devices()`` on a wedged
  tunnel blocks inside C past any Python timeout, and jax caches a failed
  backend in-process. So the parent process never initializes the backend
  blind: it spawns disposable ``python -c "import jax; jax.devices()"``
  probe children with a per-probe timeout (``RAFT_BENCH_PROBE_TIMEOUT_S``,
  default 75s) and retries until the probe budget — the total deadline
  minus a compute margin — is spent. A dead-all-round tunnel yields an
  artifact recording every attempt (≥10 across the window) instead of one
  silent 20-minute hang.
* **Persistent XLA compilation cache.** ``JAX_COMPILATION_CACHE_DIR`` is
  pointed at ``.jax_cache/`` in the repo (committed after local captures),
  so a warm driver re-run spends seconds, not minutes, compiling inside
  the tunnel window.
* **Watchdog total cap.** A daemon thread enforces an absolute wall
  deadline (``RAFT_BENCH_TOTAL_DEADLINE_S``, default 1500s from the FIRST
  exec, surviving re-exec) with ``os._exit`` so even a post-probe init
  hang still emits the artifact before the driver's rc=124.
* **Context travels with failure.** A null-value artifact embeds
  ``init_attempts`` and ``last_local_capture`` (the most recent committed
  local capture, clearly labelled — value itself stays null; no faking).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time

# Persistent compilation cache — must be in the environment before jax
# initializes. min-compile-time/entry-size floors dropped to zero so every
# executable (including the small scalar-readback helpers) is cached.
_REPO = os.path.dirname(os.path.abspath(__file__))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(_REPO, ".jax_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")

METRIC = "sintel_image_pairs_per_sec_per_chip_iters12"
UNIT = "image-pairs/sec"
BASELINE_PAIRS_PER_SEC = 10.0   # PyTorch ref, 1xV100 (see module docstring)


def _env_dim(name: str, default: int) -> int:
    """Operating-point override for explicitly-requested CPU smoke
    artifact captures (round 6: BENCH JSON regenerated on a CPU host at
    a smoke point with honest labels). Any override flips the payload's
    ``smoke_operating_point`` flag so a shrunken run can never be
    mistaken for the TPU trajectory."""
    raw = os.environ.get(name)
    return int(raw) if raw else default


H = _env_dim("RAFT_BENCH_H", 440)     # Sintel 436x1024 after pad-to-/8
W = _env_dim("RAFT_BENCH_W", 1024)
ITERS = _env_dim("RAFT_BENCH_ITERS", 12)
BATCH = _env_dim("RAFT_BENCH_BATCH", 24)
                                # materialized-arm knee (round-2 sweep:
                                # its bf16 volume pyramid OOMs at b64)
# Banded-arm operating point: the on-demand kernel stores no volume, so
# its knee sits far higher. Round-4 sweep: 82.7 @ b24, 90.7 @ b64, 93.7
# @ b128 (b64 chosen, within 3%). Round-5 re-sweep AFTER the transposed
# output store (batch_knee_probe, same day): 94.4 @ b64, 92.8 @ b96,
# **98.7 @ b128** — the tout win compounds with batch, so the headline
# arm moved to b128.
ALT_BATCH = _env_dim("RAFT_BENCH_ALT_BATCH", 128)
WARMUP = 2
REPS = _env_dim("RAFT_BENCH_REPS", 10)
_SMOKE_POINT = any(os.environ.get(k) for k in (
    "RAFT_BENCH_H", "RAFT_BENCH_W", "RAFT_BENCH_ITERS",
    "RAFT_BENCH_BATCH", "RAFT_BENCH_ALT_BATCH", "RAFT_BENCH_REPS"))
# sparse-family secondary metric: the fork's active training resolution
# (reference train_standard.sh:6: 352x480)
SPARSE_H, SPARSE_W, SPARSE_BATCH = 352, 480, 8

_EMIT_LOCK = threading.Lock()
_EMITTED = False


def _emit(payload: dict) -> bool:
    """Print the one-and-only JSON artifact line (first caller wins —
    the watchdog thread may race the success path).  The print happens
    INSIDE the lock so a losing watchdog blocks here until the winning
    line is fully flushed before it ``os._exit``s."""
    global _EMITTED
    with _EMIT_LOCK:
        if _EMITTED:
            return False
        _EMITTED = True
        print(json.dumps(payload), flush=True)
    return True


_PLATFORM: str | None = None   # set once the backend is up, for triage
_HEADLINE: dict | None = None  # completed headline numbers, survive a
                               # failure in the secondary metric
_INIT_ATTEMPTS: list[dict] = []  # probe-ladder log, embedded in artifacts
try:
    # survive the one re-exec retry (see _wait_for_backend) so the
    # artifact records every attempt, not just post-exec ones
    _INIT_ATTEMPTS.extend(
        json.loads(os.environ.get("RAFT_BENCH_ATTEMPT_LOG", "[]")))
except ValueError:
    pass


def _last_local_capture() -> dict | None:
    """Most recent committed local capture, embedded in failure artifacts
    so the context travels with the null (the value stays null — this is
    labelled context, not a substitute measurement)."""
    for name in ("BENCH_local.json", "BENCH_r03_local.json",
                 "BENCH_r02_local.json"):
        path = os.path.join(_REPO, name)
        try:
            with open(path) as f:
                lines = [l for l in f if l.strip()]
            # last non-empty line: tunnel_watch copies bench stdout
            # verbatim, and third-party libraries may have printed
            # above the artifact line
            data = json.loads(lines[-1]) if lines else None
        except (OSError, ValueError):
            continue
        if isinstance(data, dict) and data.get("value") is not None:
            return {"file": name, **data}
    return None


def _emit_failure(msg: str) -> None:
    """Terminal failure still yields one parseable JSON artifact line.
    If the headline measurement already completed (only a secondary
    metric was in flight), its numbers are published with the error
    attached rather than thrown away.  Includes the platform when known
    so a CPU-fallback timeout is not misread as a tunnel hang."""
    payload = dict(_HEADLINE) if _HEADLINE is not None else {
        "metric": METRIC,
        "value": None,
        "unit": UNIT,
        "vs_baseline": None,
    }
    payload["error"] = msg
    if _PLATFORM is not None:
        payload.setdefault("platform", _PLATFORM)
    if _INIT_ATTEMPTS:
        # distinct key from the success artifact's int init_attempt_count
        # so the field never flips type between artifacts
        payload["init_attempt_log"] = _INIT_ATTEMPTS
    if payload.get("value") is None:
        local = _last_local_capture()
        if local is not None:
            payload["last_local_capture"] = local
    _emit(payload)


class _Watchdog:
    """Hard wall-clock deadline surviving re-exec retries.

    ``jax.devices()`` on a wedged tunnel can block inside
    ``xla_client.make_c_api_client`` for 10+ minutes, beyond any Python
    try/except — only a watchdog thread + ``os._exit`` reliably gets the
    JSON line out before the driver's own timeout (rc=124, no artifact).

    One absolute cap (``RAFT_BENCH_TOTAL_DEADLINE_S``, default 1500s),
    anchored to the FIRST exec start time (``RAFT_BENCH_START`` env,
    preserved across re-exec) so the whole process fits inside the
    driver's kill window (round-1 evidence puts that window near 30 min).
    The init phase is additionally bounded by the probe ladder itself
    (:func:`_wait_for_backend`), which never blocks in C.
    """

    def __init__(self) -> None:
        total_s = float(
            os.environ.get("RAFT_BENCH_TOTAL_DEADLINE_S", "1500"))
        self.start = float(os.environ.setdefault("RAFT_BENCH_START",
                                                 str(time.time())))
        self.total_expiry = self.start + total_s
        self._expiry = self.total_expiry
        self._reason = "total wall cap"
        if time.time() >= self._expiry:
            _emit_failure(f"deadline {total_s:.0f}s exceeded before start")
            os._exit(0)
        threading.Thread(target=self._watch, daemon=True).start()

    def lift(self) -> None:
        # Explicitly-requested CPU smoke runs are interactive, not
        # driver artifacts; full-size CPU compute takes hours and
        # must not be misreported as an accelerator hang.
        self._expiry = float("inf")

    def _watch(self) -> None:
        while True:
            remaining = self._expiry - time.time()
            if remaining <= 0:
                try:
                    _emit_failure(
                        f"{self._reason} deadline exceeded "
                        f"(accelerator hang?)")
                except BaseException:   # artifact at any cost
                    try:
                        _emit({"metric": METRIC, "value": None,
                               "unit": UNIT, "vs_baseline": None,
                               "error": "watchdog emit failed"})
                    except BaseException:
                        pass
                os._exit(0)
            time.sleep(min(remaining, 5.0))


def _probe_backend(timeout_s: float) -> tuple[bool, str]:
    """Check backend health in a disposable child process.  The child —
    not the parent — eats any in-C init hang; the parent reliably times
    it out and kills it.  Returns (ok, platform-or-error)."""
    # The axon plugin pins jax_platforms in jax.config at interpreter
    # startup, overriding the env var — re-apply JAX_PLATFORMS explicitly
    # so a requested CPU run really probes CPU (see tests/conftest.py).
    # A silent accelerator→CPU *fallback* is a probe failure, not
    # success: committing to a full-size CPU bench is a guaranteed
    # watchdog timeout, exactly what the ladder exists to avoid.
    code = ("import os, jax, sys\n"
            "p = os.environ.get('JAX_PLATFORMS')\n"
            "if p: jax.config.update('jax_platforms', p)\n"
            "plat = jax.devices()[0].platform\n"
            "if plat == 'cpu' and not (p or '').startswith('cpu'):\n"
            "    sys.stderr.write('silent CPU fallback')\n"
            "    sys.exit(3)\n"
            "sys.stdout.write(plat)")
    env = dict(os.environ)
    env.pop("RAFT_BENCH_START", None)   # child is a probe, not a bench
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout_s, env=env)
    except subprocess.TimeoutExpired:
        return False, f"probe timeout {timeout_s:.0f}s"
    except OSError as e:
        return False, f"probe spawn failed: {e}"
    if r.returncode == 0 and r.stdout.strip():
        return True, r.stdout.strip()
    tail = (r.stderr or "").strip().splitlines()
    return False, (tail[-1][-200:] if tail else f"rc={r.returncode}")


def _wait_for_backend(watchdog: _Watchdog) -> bool:
    """Probe ladder: many short, killable init attempts spread across the
    window, instead of one long blind wait.  Probing stops when less than
    ``RAFT_BENCH_COMPUTE_MARGIN_S`` (default 420s; a warm compile cache
    needs far less) remains before the total deadline, reserving room for
    the real compile + measurement after a late success.

    On probe success the parent initializes its own backend (covered by
    the watchdog; one re-exec retry if that init *errors* — jax caches a
    failed backend in-process).  Returns True iff the run is an
    *explicitly requested* CPU run (local smoke) — the caller uses this
    to lift the watchdog's wall cap."""
    global _PLATFORM
    probe_timeout = float(os.environ.get("RAFT_BENCH_PROBE_TIMEOUT_S", "75"))
    retry_s = float(os.environ.get("RAFT_BENCH_RETRY_S", "15"))
    margin_s = float(os.environ.get("RAFT_BENCH_COMPUTE_MARGIN_S", "420"))
    # A short caller-set total deadline must still yield >=1 real probe:
    # cap the margin at a third of the remaining window.
    margin_s = min(margin_s, (watchdog.total_expiry - time.time()) / 3.0)
    probe_budget_end = watchdog.total_expiry - margin_s

    attempt = len(_INIT_ATTEMPTS)
    while True:
        attempt += 1
        now = time.time()
        budget = probe_budget_end - now
        if budget <= 0:
            _emit_failure(
                f"accelerator backend unavailable after {attempt - 1} "
                f"probe attempts spanning "
                f"{now - watchdog.start:.0f}s")
            sys.exit(0)
        ok, info = _probe_backend(min(probe_timeout, budget))
        _INIT_ATTEMPTS.append({
            "t_s": round(time.time() - watchdog.start, 1),
            "ok": ok, "info": info})
        if ok:
            break
        print(f"backend probe {attempt} failed: {info}; "
              f"retrying in {retry_s:.0f}s "
              f"({probe_budget_end - time.time():.0f}s of probe budget "
              f"left)", file=sys.stderr, flush=True)
        time.sleep(min(retry_s, max(0.0, probe_budget_end - time.time())))

    # Probe says healthy — initialize in-process. A hang here is caught
    # by the watchdog; an *error* (jax poisons a failed backend) gets one
    # re-exec, deadline still anchored to first exec via RAFT_BENCH_START.
    import jax
    env_platforms = os.environ.get("JAX_PLATFORMS")
    if env_platforms:
        # Same plugin-pinned-config override as the probe child.
        jax.config.update("jax_platforms", env_platforms)
    try:
        dev = jax.devices()[0]
    except Exception as e:
        # distinct retry flags per failure reason so diagnostics don't
        # conflate one init error + one CPU fallback into "(twice)"
        if os.environ.get("RAFT_BENCH_INIT_TRY"):
            _emit_failure(f"backend init failed after healthy probe "
                          f"(twice): {e}")
            sys.exit(0)
        print(f"init failed after healthy probe: {e}; re-exec once",
              file=sys.stderr, flush=True)
        os.environ["RAFT_BENCH_INIT_TRY"] = "1"
        os.environ["RAFT_BENCH_ATTEMPT_LOG"] = json.dumps(_INIT_ATTEMPTS)
        os.execv(sys.executable, [sys.executable] + sys.argv)
    _PLATFORM = dev.platform
    requested = (os.environ.get("JAX_PLATFORMS")
                 or str(getattr(jax.config, "jax_platforms", "") or ""))
    cpu_explicit = requested.startswith("cpu")
    if dev.platform == "cpu" and not cpu_explicit:
        # Silent accelerator→CPU fallback: mirror the probe child's
        # policy (a full-size CPU bench is a guaranteed watchdog timeout
        # with a misleading error). One re-exec retry — the tunnel may
        # have flapped between probe and init — then a clean failure
        # artifact while probe budget still remains.
        if os.environ.get("RAFT_BENCH_CPU_TRY"):
            _emit_failure("silent CPU fallback after healthy probe "
                          "(twice)")
            sys.exit(0)
        print("accelerator fell back to CPU after healthy probe; "
              "re-exec once", file=sys.stderr, flush=True)
        os.environ["RAFT_BENCH_CPU_TRY"] = "1"
        os.environ["RAFT_BENCH_ATTEMPT_LOG"] = json.dumps(_INIT_ATTEMPTS)
        os.execv(sys.executable, [sys.executable] + sys.argv)
    return dev.platform == "cpu" and cpu_explicit


def kernel_ab_arm(payload: dict, key: str, arms, measure, platform: str):
    """Shared fused-kernel A/B arm (knee-provenance discipline like the
    banded-vs-all-pairs arms): run ``measure()`` once per arm with that
    arm's trace-time env flags forced, recording each reading as
    ``value_{key}_{label}``. ``arms`` is ``((label, {FLAG: val, ...}),
    ...)`` — each arm's flags are forced together via ``forced_flag``
    (one ExitStack per arm) so the arm traces a fresh executable, and
    the surrounding env is restored afterwards so later sections run
    the ambient dispatch. ``measure`` must build a FRESH ``jax.jit``
    per call: the flags are trace-time, so reusing a jitted callable
    across arms would silently serve the first arm's executable. A
    failed arm records ``{key}_{label}_error`` and its siblings
    survive. On CPU the forced-pallas arms run kernels under the
    Pallas interpreter — a parity tool, not a fast path — so a
    pallas<xla reading on a cpu-labelled artifact is expected and
    honest (kernel_ab_note says so in-band)."""
    import contextlib

    from raft_tpu.utils.envflags import forced_flag
    for label, env in arms:
        with contextlib.ExitStack() as stack:
            for flag, val in env.items():
                stack.enter_context(forced_flag(flag, val))
            try:
                payload[f"value_{key}_{label}"] = round(measure(), 3)
            except Exception as e:   # the sibling arm must survive
                payload[f"{key}_{label}_error"] = (
                    f"{type(e).__name__}: {e}")
    if platform == "cpu":
        payload["kernel_ab_note"] = (
            "cpu capture: forced-pallas arms run under the Pallas "
            "interpreter — interpret-mode parity evidence, not a "
            "fast path; speed deltas are TPU measurements")


def main(gru: str = "ab", motion: str = "ab"):
    watchdog = _Watchdog()
    cpu_smoke = _wait_for_backend(watchdog)
    if cpu_smoke:
        watchdog.lift()
    import jax
    import jax.numpy as jnp
    from raft_tpu.config import RAFTConfig
    from raft_tpu.models.raft import RAFT

    # TPU-first inference policy: bf16 encoders/update, f32 corr volume.
    platform = jax.devices()[0].platform
    cfg = RAFTConfig(iters=ITERS, mixed_precision=(platform == "tpu"))
    model = RAFT(cfg)
    rng = jax.random.PRNGKey(0)
    img1 = jax.random.uniform(rng, (1, H, W, 3), jnp.float32) * 255.0
    variables = model.init({"params": rng, "dropout": rng}, img1, img1,
                           iters=1)

    @jax.jit
    def fwd(i1, i2):
        # Scalar-reduce the flow so syncing is a 4-byte host readback:
        # block_until_ready alone has returned early through the tunnel.
        flow_up = model.apply(variables, i1, i2, test_mode=True)[1]
        return flow_up, jnp.sum(flow_up)

    def throughput(batch: int, fwd_fn=None) -> float:
        fwd_fn = fwd_fn or fwd
        img = jnp.broadcast_to(img1, (batch, H, W, 3))
        for _ in range(WARMUP):
            float(fwd_fn(img, img)[1])
        # Dispatch all reps, sync once — measures device pipeline rate
        # (how eval/training actually stream batches), not the host↔device
        # round-trip latency of a lone request.
        # Keep only the newest output reference: execution is async, so
        # reps still pipeline back-to-back, but earlier ~86 MB flow
        # buffers are freed as they complete instead of 10 being pinned.
        t0 = time.perf_counter()
        for _ in range(REPS):
            out = fwd_fn(img, img)
        float(out[1])
        return REPS * batch / (time.perf_counter() - t0)

    global _HEADLINE
    # Headline FIRST: if the tunnel dies mid-run, the watchdog publishes
    # whatever _HEADLINE holds — the primary metric must land before any
    # secondary measurement spends wall clock. The materialized-volume
    # arm runs first as the provisional headline (it has three rounds of
    # on-chip history and zero compile risk); the on-demand banded arm
    # then PROMOTES itself to the headline if it succeeds — since round
    # 4 it is the framework's eval-default engine (corr_impl="auto";
    # measured 84.3 vs 56.1 pairs/s at Sintel and 22.2 vs 18.4 at KITTI,
    # BASELINE.md), and the reference itself sanctions the on-demand
    # path as a first-class eval option (core/corr.py:64-92, README
    # --alternate_corr). A failed banded arm leaves the materialized
    # headline standing — the artifact is always valid.
    pairs_per_sec = throughput(BATCH)
    payload = {
        "metric": METRIC,
        "value": round(pairs_per_sec, 3),
        "unit": UNIT,
        "batch": BATCH,
        "platform": platform,
        "vs_baseline": round(pairs_per_sec / BASELINE_PAIRS_PER_SEC, 3),
        "value_all_pairs": round(pairs_per_sec, 3),
        "headline_engine": "all_pairs",
        "init_attempt_count": len(_INIT_ATTEMPTS),
        # Fused-kernel dispatches the headline ran under (trace-time;
        # 'auto' = fused Pallas kernel on TPU when eligible)
        "gru": os.environ.get("RAFT_GRU_PALLAS") or "auto",
        "motion": os.environ.get("RAFT_MOTION_PALLAS") or "auto",
        "resolution": f"{H}x{W}",
        "iters": ITERS,
        "reps": REPS,
    }
    if _SMOKE_POINT:
        # env-shrunken operating point (CPU artifact capture): mark it so
        # this line is never read as the TPU trajectory
        payload["smoke_operating_point"] = True
    # From here on a watchdog fire publishes the headline numbers.
    # Snapshot (never alias) — the watchdog thread reads _HEADLINE while
    # main keeps mutating payload with secondary-metric keys, and
    # dict()-copying a dict being resized concurrently raises.
    _HEADLINE = dict(payload)
    headline_fwd = fwd
    headline_model = model
    if platform != "cpu":
        # On-demand banded-correlation arm (identical numerics, asserted
        # by tests): per iteration it touches only each query tile's
        # y-band of the target features instead of re-reading the
        # materialized volume pyramid. run_with_band_retry walks the
        # dynamic → masked-static → off fallback ladder and records
        # which mode produced the numbers (alternate_band /
        # alternate_band_{mode}_error keys).
        from raft_tpu.ops.corr_pallas import run_with_band_retry
        cfga = RAFTConfig(iters=ITERS,
                          mixed_precision=(platform == "tpu"),
                          alternate_corr=True)
        modela = RAFT(cfga)
        alt_jit = []

        def alternate_arm():
            def fwda(i1, i2):
                flow_up = modela.apply(variables, i1, i2,
                                       test_mode=True)[1]
                return flow_up, jnp.sum(flow_up)

            jfwda = jax.jit(fwda)
            rate = throughput(ALT_BATCH, jfwda)
            payload["value_alternate_corr"] = round(rate, 3)
            alt_jit.append((jfwda, rate))

        if run_with_band_retry(alternate_arm, payload, "alternate"):
            headline_fwd, alt_rate = alt_jit[-1]
            headline_model = modela
            payload["value"] = round(alt_rate, 3)
            payload["vs_baseline"] = round(
                alt_rate / BASELINE_PAIRS_PER_SEC, 3)
            payload["headline_engine"] = "alternate_banded"
            payload["batch"] = ALT_BATCH
            payload["batch_all_pairs"] = BATCH
            # Pin the surviving band rung for the rest of the process:
            # batch1 below re-traces the promoted engine at batch 1, and
            # without this it would re-try the default dynamic mode even
            # when the ladder had to fall back (and the recorded
            # alternate_band would no longer describe what batch1 ran).
            os.environ["RAFT_CORR_BAND"] = {
                "dynamic": "1", "static": "static",
                "off": "0"}[payload["alternate_band"]]
        _HEADLINE = dict(payload)
    try:
        # single-pair throughput on the headline engine, apples-to-apples
        # with the latency-bound 10 pairs/sec V100 estimate the baseline
        # is normalized to
        batch1 = throughput(1, headline_fwd)
        payload["value_batch1"] = round(batch1, 3)
        payload["vs_baseline_batch1"] = round(
            batch1 / BASELINE_PAIRS_PER_SEC, 3)
    except Exception as e:
        payload["batch1_error"] = f"{type(e).__name__}: {e}"
    _HEADLINE = dict(payload)

    def early_exit_arm():
        # Iterate-to-convergence arm: re-trace the headline engine with
        # the masked convergence exit threaded into the refine scan and
        # measure the SAME operating point. iters_saved is the measured
        # per-sample (ITERS - iters_used) — what the tolerance says the
        # fixed-count loop overspends — while value_early_exit shows
        # what the masking itself costs in throughput (the masked scan
        # still runs full length with converged samples frozen, so this
        # arm measures the accounting the serving quality ladder feeds
        # on, not a wall-clock shortcut).
        tol = float(os.environ.get("RAFT_BENCH_EE_TOL", "0.1"))
        patience = int(os.environ.get("RAFT_BENCH_EE_PATIENCE", "2"))

        def fwde(i1, i2, m=headline_model):
            _, flow_up, used = m.apply(variables, i1, i2,
                                       test_mode=True,
                                       early_exit=(tol, patience))
            return flow_up, jnp.sum(flow_up), used

        jfwde = jax.jit(fwde)
        payload["value_early_exit"] = round(
            throughput(payload["batch"], jfwde), 3)
        img = jnp.broadcast_to(img1, (payload["batch"], H, W, 3))
        used = jax.device_get(jfwde(img, img)[2])
        payload["early_exit"] = {"tol": tol, "patience": patience}
        payload["iters_saved"] = {
            "mean": round(float(ITERS - used.mean()), 3),
            "min": int(ITERS - used.max()),
            "max": int(ITERS - used.min()),
            "iters": ITERS,
        }

    try:
        early_exit_arm()
    except Exception as e:   # secondary arm must never sink the artifact
        payload["early_exit_error"] = f"{type(e).__name__}: {e}"
    _HEADLINE = dict(payload)

    def headline_ab(key: str, flag: str):
        # Headline-engine A/B pass through the module-level
        # kernel_ab_arm helper: re-trace the headline model with the
        # named Pallas kernel forced ON ('1') and OFF ('0') and record
        # both readings as value_{key}_{pallas,xla}. measure() builds a
        # fresh jit per arm (trace-time flag — see the helper).
        def measure():
            def fwdk(i1, i2, m=headline_model):
                flow_up = m.apply(variables, i1, i2,
                                  test_mode=True)[1]
                return flow_up, jnp.sum(flow_up)

            return throughput(payload["batch"], jax.jit(fwdk))

        kernel_ab_arm(payload, key,
                      (("pallas", {flag: "1"}), ("xla", {flag: "0"})),
                      measure, platform)

    if gru == "ab":
        headline_ab("gru", "RAFT_GRU_PALLAS")
        _HEADLINE = dict(payload)

    if motion == "ab":
        # Round-7 motion-encoder arm, same contract as the GRU arm.
        headline_ab("motion", "RAFT_MOTION_PALLAS")
        _HEADLINE = dict(payload)

    if platform == "cpu":
        # full-size secondaries on CPU take hours; they are TPU
        # measurements, not part of the CPU smoke contract
        payload["sparse_skipped"] = "cpu"
    else:
        try:
            # A/B arm: force the old float32 volume storage. The
            # materialized arm's corr_dtype="auto" resolves to bf16
            # storage at inference under mixed precision (round-3
            # default flip — measured flow delta mean 0.0026 px at
            # Sintel res, BASELINE.md), so the f32 arm documents what
            # the lever buys. corr_dtype only changes storage, not
            # parameters, so the headline's variables are reused — no
            # second eager init.
            cfg32 = RAFTConfig(iters=ITERS,
                               mixed_precision=(platform == "tpu"),
                               corr_dtype="float32")
            model32 = RAFT(cfg32)

            @jax.jit
            def fwd32(i1, i2):
                flow_up = model32.apply(variables, i1, i2,
                                        test_mode=True)[1]
                return flow_up, jnp.sum(flow_up)

            payload["value_f32_volume"] = round(
                throughput(BATCH, fwd32), 3)
        except Exception as e:
            payload["f32_volume_error"] = f"{type(e).__name__}: {e}"
        _HEADLINE = dict(payload)   # refresh snapshot between sections
        try:
            payload.update(_sparse_metrics())
        except Exception as e:  # secondary must never sink the artifact
            payload["sparse_error"] = f"{type(e).__name__}: {e}"
    _emit(payload)


def _sparse_metrics() -> dict:
    """Secondary metric: SparseRAFT forward throughput at the fork's
    active training resolution (352x480, ``train_standard.sh:6``).
    Same dispatch/sync discipline as the headline metric."""
    import jax
    import jax.numpy as jnp
    from raft_tpu.config import OursConfig, sparse_corr_from_env
    from raft_tpu.models import SparseRAFT

    platform = jax.devices()[0].platform
    h, w, batch = SPARSE_H, SPARSE_W, SPARSE_BATCH
    model = SparseRAFT(OursConfig(mixed_precision=(platform == "tpu"),
                                  alternate_corr=sparse_corr_from_env()))
    rng = jax.random.PRNGKey(0)
    img = jax.random.uniform(rng, (batch, h, w, 3), jnp.float32) * 255.0
    variables = model.init({"params": rng, "dropout": rng}, img, img)

    @jax.jit
    def fwd(i1, i2):
        flow_low, flow_up = model.apply(variables, i1, i2, test_mode=True)
        return jnp.sum(flow_up)

    for _ in range(WARMUP):
        float(fwd(img, img))
    t0 = time.perf_counter()
    for _ in range(REPS):
        out = fwd(img, img)
    float(out)
    rate = REPS * batch / (time.perf_counter() - t0)
    return {"sparse_forward_pairs_per_sec": round(rate, 3),
            "sparse_batch": batch, "sparse_resolution": [h, w]}


STEP_METRIC = "fused_step_vs_chained_pairs_per_sec_speedup"

# Trace-time env for each refine-step arm. 'fused' forces the
# one-launch chained motion-encoder→GRU(→flow-head) kernel
# (ops/step_pallas.py); 'chained' forces the two per-kernel launches it
# replaces — the packed [motion‖flow] handoff buffer round-trips HBM
# between them every refine iteration; 'xla' turns all three off (the
# pure XLA conv path both kernels are tested bit-compatible against).
STEP_ARM_ENVS = (
    ("fused", {"RAFT_STEP_PALLAS": "1"}),
    ("chained", {"RAFT_STEP_PALLAS": "0",
                 "RAFT_MOTION_PALLAS": "1",
                 "RAFT_GRU_PALLAS": "1"}),
    ("xla", {"RAFT_STEP_PALLAS": "0",
             "RAFT_MOTION_PALLAS": "0",
             "RAFT_GRU_PALLAS": "0"}),
)


def step_main(arm: str = "ab"):
    """``python bench.py --step {ab,fused,chained,xla}`` — one-launch
    refine-iteration benchmark (round 10, BENCH_r10).

    ``ab`` (the committed-artifact arm) measures the SAME headline
    forward (RAFT-large, test_mode, headline operating point) under all
    three ``STEP_ARM_ENVS`` dispatches and publishes the fused/chained
    throughput ratio as the headline value, with every arm's reading in
    ``per_arm``. ``fused``/``chained``/``xla`` run a single arm for
    debugging (value stays null — a ratio needs both measurements).

    Alongside wall-clock, each Pallas arm carries the host-independent
    claim the fusion actually makes: ``handoff_hbm_bytes_per_iter``,
    the per-refine-iteration HBM traffic of the motion→GRU handoff.
    The chained arm writes the packed ``[motion‖flow]`` buffer
    (``B·(H/8)·(W/8)·128`` values) out of the motion launch and reads
    it back into the GRU launch — one write + one read per iteration;
    the fused arm keeps it VMEM-resident (0 bytes). The xla arm's
    traffic is left null: XLA's own fusion decisions are not modeled
    here, and a guessed number would impersonate a measurement."""
    watchdog = _Watchdog()
    cpu_smoke = _wait_for_backend(watchdog)
    if cpu_smoke:
        watchdog.lift()
    import jax
    import jax.numpy as jnp
    from raft_tpu.config import RAFTConfig
    from raft_tpu.models.raft import RAFT

    platform = jax.devices()[0].platform
    cfg = RAFTConfig(iters=ITERS, mixed_precision=(platform == "tpu"))
    model = RAFT(cfg)
    rng = jax.random.PRNGKey(0)
    img1 = jax.random.uniform(rng, (1, H, W, 3), jnp.float32) * 255.0
    variables = model.init({"params": rng, "dropout": rng}, img1, img1,
                           iters=1)

    def throughput(batch: int, fwd_fn) -> float:
        # Same dispatch/sync discipline as the headline metric: WARMUP
        # synced runs, then REPS back-to-back dispatches, one readback.
        img = jnp.broadcast_to(img1, (batch, H, W, 3))
        for _ in range(WARMUP):
            float(fwd_fn(img, img)[1])
        t0 = time.perf_counter()
        for _ in range(REPS):
            out = fwd_fn(img, img)
        float(out[1])
        return REPS * batch / (time.perf_counter() - t0)

    def measure():
        # Fresh jit per arm — the step/motion/gru flags are trace-time,
        # so each arm must build its own executable (see kernel_ab_arm).
        def fwdk(i1, i2):
            flow_up = model.apply(variables, i1, i2, test_mode=True)[1]
            return flow_up, jnp.sum(flow_up)

        return throughput(BATCH, jax.jit(fwdk))

    # Handoff arithmetic (see docstring). The packed buffer is 128
    # channels (126 motion + 2 flow, ops/layout.py invariant 6) in the
    # refine chain's compute dtype: bf16 under mixed precision (TPU),
    # f32 on the smoke hosts.
    dtype_bytes = 2 if platform == "tpu" else 4
    handoff_bytes = 2 * BATCH * (H // 8) * (W // 8) * 128 * dtype_bytes

    arms = (STEP_ARM_ENVS if arm == "ab"
            else tuple(a for a in STEP_ARM_ENVS if a[0] == arm))
    payload = {
        "metric": STEP_METRIC,
        "value": None,
        "unit": "x",
        "batch": BATCH,
        "platform": platform,
        "resolution": f"{H}x{W}",
        "iters": ITERS,
        "reps": REPS,
        "step_arm": arm,
        "handoff_channels": 128,
        "handoff_dtype_bytes": dtype_bytes,
    }
    kernel_ab_arm(payload, "step", arms, measure, platform)

    per_arm = {}
    for label, _env in arms:
        rec = {}
        rate = payload.pop(f"value_step_{label}", None)
        err = payload.pop(f"step_{label}_error", None)
        if rate is not None:
            rec["pairs_per_sec"] = rate
        if err is not None:
            rec["error"] = err
        if label == "fused":
            rec["handoff_hbm_bytes_per_iter"] = 0
        elif label == "chained":
            rec["handoff_hbm_bytes_per_iter"] = handoff_bytes
        else:               # xla: not modeled — see docstring
            rec["handoff_hbm_bytes_per_iter"] = None
        per_arm[label] = rec
    payload["per_arm"] = per_arm

    fused = per_arm.get("fused", {}).get("pairs_per_sec")
    chained = per_arm.get("chained", {}).get("pairs_per_sec")
    if fused and chained:
        payload["value"] = round(fused / chained, 3)
    if platform != "tpu":
        payload["smoke_operating_point"] = True
        payload["criterion_note"] = (
            "cpu capture: both Pallas arms run under the Pallas "
            "interpreter, so the wall-clock ratio is plumbing/parity "
            "evidence (three distinct executables, same numbers), not "
            "the TPU speedup. The host-independent claim is the "
            "handoff arithmetic: the chained arm round-trips the "
            "packed [motion‖flow] buffer through HBM every refine "
            "iteration (handoff_hbm_bytes_per_iter) while the fused "
            "arm keeps it VMEM-resident; the on-TPU capture is "
            "tracked as ROADMAP debt")
    _emit(payload)


def _step_failure(msg: str) -> None:
    _emit({"metric": STEP_METRIC, "value": None, "unit": "x",
           "error": msg})


SERVING_METRIC = "serving_vs_sequential_batch1_speedup"


def serving_main(replicas: int = 1, trace: bool = False):
    """``python bench.py serving [--replicas N]`` — dynamic-batching
    serving benchmark.

    Drives the serving engine (raft_tpu/serving/) with concurrent
    closed-loop clients and publishes its sustained throughput against
    the thing it replaces: a sequential batch-1 request loop over the
    SAME predictor on the same host. Emits ONE BENCH-compatible JSON
    line (same contract as the headline mode).

    Operating point is platform-adaptive: on TPU the flagship RAFT-large
    at Sintel resolution / iters=12 (the batch-1 gap this subsystem
    exists to close — BENCH_r05: 31.5 pairs/s at b1 vs 99.0 at b128);
    on CPU a small-model smoke point that completes in minutes and
    STILL verifies every response bit-for-bit. CPU hosts with one core
    (this container) have no dispatch gap to recover — the artifact says
    so explicitly in ``criterion_note`` instead of faking a speedup.

    ``--replicas N`` (default 1) serves through an N-replica
    :class:`~raft_tpu.serving.fleet.ServingFleet` instead of one
    engine. The artifact records ``replicas``, a ``topology`` label
    (``single-replica`` keeps the existing single-engine trajectory
    comparable across rounds) and per-replica warmup time/compiles —
    on one host extra replicas add routing, not compute, so the
    interesting numbers are the warmup-sharing and failover machinery,
    not the throughput.

    ``--trace`` enables request-scoped tracing for the run and writes
    the Perfetto-loadable Chrome trace JSON next to the bench; its path
    ships in the artifact as ``trace_artifact`` (validated by
    ``scripts/check_bench_schema.py``: the file must exist and parse as
    trace JSON). Off by default — the headline numbers stay measured on
    the zero-instrumentation path.
    """
    import jax

    from raft_tpu.evaluate import load_predictor
    from raft_tpu.serving import (ServingConfig, ServingEngine, loadgen,
                                  make_fleet)

    platform = jax.devices()[0].platform
    ncores = os.cpu_count() or 1
    if platform == "tpu":
        shapes = [(436, 1024)]
        small, iters = False, ITERS
        max_batch, concurrency, n_requests = 32, 16, 512
        max_wait_ms = 5.0
    else:
        shapes = [(64, 96), (61, 93)]     # two raws, one padded bucket
        small, iters = True, 4
        max_batch, concurrency, n_requests = 8, 8, 48
        max_wait_ms = 4.0

    tracer = None
    if trace:
        from raft_tpu.observability import enable_tracing
        tracer = enable_tracing()   # before engine build: captured at init

    predictor = load_predictor("random", small=small, iters=iters)
    frames = loadgen.make_frames(shapes, per_shape=2, seed=0)
    refs = loadgen.batched_reference_flows(frames=frames,
                                           predictor=predictor,
                                           max_batch=max_batch)
    seq = loadgen.sequential_baseline(predictor, frames,
                                      n_requests=max(n_requests // 4, 8))

    cfg = ServingConfig(
        max_batch=max_batch, max_wait_ms=max_wait_ms,
        buckets=tuple(shapes), persistent_cache=True)
    if replicas <= 1:
        engine = ServingEngine(predictor, cfg)
        t0 = time.perf_counter()
        warm = engine.warmup()
        warmup_per_replica = {"r0": {
            "seconds": round(time.perf_counter() - t0, 3),
            "compiles": int(sum(v["compiles"] for v in warm.values()))}}
        engine.start(warmup=False)
        server, metrics_owner = engine, engine.metrics
        host_stage_ms = engine.stages.summary()
        mean_batch = engine.metrics.mean_batch_size
        padded_slots = lambda: engine.metrics.padded_slots  # noqa: E731
        queue_peak = lambda: engine.metrics.queue_depth_peak  # noqa: E731
        compiles = lambda: engine.metrics.compiles  # noqa: E731
        quality_hist = engine.metrics.quality_histogram
        early_exit_saved = lambda: (  # noqa: E731
            engine.metrics.early_exit_iters_saved)
        close = engine.close
    else:
        fleet = make_fleet(predictor, replicas, cfg)
        fleet.start(warm_spares=True)
        warmup_per_replica = {
            rid: {k: (round(v, 3) if isinstance(v, float) else v)
                  for k, v in stats.items()}
            for rid, stats in fleet.warmup_stats.items()}
        engines = fleet.engines.values()
        server, metrics_owner = fleet, fleet.metrics

        def mean_batch():
            hist = fleet.metrics.batch_histogram()
            n = sum(hist.values())
            return (sum(k * v for k, v in hist.items()) / n) if n else 0.0

        host_stage_ms = None   # filled post-run, per replica
        padded_slots = lambda: sum(  # noqa: E731
            e.metrics.padded_slots for e in engines)
        queue_peak = lambda: max(  # noqa: E731
            e.metrics.queue_depth_peak for e in engines)
        compiles = lambda: sum(  # noqa: E731
            e.metrics.compiles for e in engines)

        def quality_hist():
            merged = {}
            for e in engines:
                for k, v in e.metrics.quality_histogram().items():
                    merged[k] = merged.get(k, 0) + v
            return merged

        early_exit_saved = lambda: sum(  # noqa: E731
            e.metrics.early_exit_iters_saved for e in engines)
        close = fleet.close

    try:
        res = loadgen.run_load(server, frames, n_requests=n_requests,
                               concurrency=concurrency, references=refs)
    finally:
        close()
    if host_stage_ms is None:
        host_stage_ms = {rid: e.stages.summary()
                         for rid, e in fleet.engines.items()}

    speedup = (res["throughput_rps"] / seq["throughput_rps"]
               if seq["throughput_rps"] else None)
    payload = {
        "metric": SERVING_METRIC,
        "value": round(speedup, 3) if speedup else None,
        "unit": "x",
        "platform": platform,
        "host_cores": ncores,
        "model": "raft-small" if small else "raft-large",
        "iters": iters,
        "shapes": [list(s) for s in shapes],
        "n_requests": n_requests,
        "concurrency": concurrency,
        "max_batch": max_batch,
        "max_wait_ms": max_wait_ms,
        "replicas": replicas,
        "topology": ("single-replica" if replicas <= 1
                     else f"fleet-{replicas}"),
        "warmup_per_replica": warmup_per_replica,
        "serving_pairs_per_sec": round(res["throughput_rps"], 3),
        "sequential_batch1_pairs_per_sec": round(
            seq["throughput_rps"], 3),
        "latency_p50_ms": round(res["latency_ms"]["p50"], 2),
        "latency_p95_ms": round(res["latency_ms"]["p95"], 2),
        "latency_p99_ms": round(res["latency_ms"]["p99"], 2),
        "batch_histogram": {str(k): v for k, v in
                            sorted(res["batch_histogram"].items())},
        "mean_batch_size": round(mean_batch(), 2),
        "padded_slots": padded_slots(),
        "queue_depth_peak": queue_peak(),
        "post_warmup_compiles": compiles(),
        # Served-quality accounting (graceful brownout): which GRU
        # iteration counts responses were actually served at. With no
        # iters_ladder configured this is all full quality — the key
        # still ships so round-over-round artifacts are comparable.
        "quality_histogram": {str(k): v for k, v in
                              sorted(quality_hist().items(),
                                     reverse=True)},
        "early_exit_iters_saved": early_exit_saved(),
        "iters_ladder": list(cfg.iters_ladder),
        "responses_bit_exact": res["ok"],
        "dropped": len(res["dropped"]),
        "mismatched": len(res["mismatched"]),
        "host_stage_ms": host_stage_ms,
    }
    if tracer is not None:
        payload["trace_artifact"] = tracer.write(
            "/tmp/raft_bench_serving_trace.json")
    if replicas > 1:
        snap = metrics_owner.snapshot()
        payload["fleet"] = {
            "routed": int(snap["fleet_routed"]),
            "failovers": int(snap["fleet_failovers"]),
            "retries": int(snap["fleet_retries"]),
            "shed": int(snap["fleet_shed"]),
            "per_replica_routed": {
                rid: int(snap[f"fleet_{rid}_routed"])
                for rid in fleet.replica_ids},
        }
    if platform != "tpu":
        # Honesty clause (bench.py discipline: context travels with the
        # artifact, values are never faked): the batch-1 gap is a device
        # dispatch-overhead phenomenon. A 1-core CPU host is
        # compute-bound at every batch size, so the ≥2x criterion is
        # measurable only on an accelerator — the committed TPU context
        # below is what serving recovers there, not this host's number.
        payload["criterion_note"] = (
            "≥2x speedup is an accelerator dispatch-bound phenomenon; "
            f"this {ncores}-core {platform} host is compute-bound at "
            "every batch size (measured b8/b1 ratio ~1.0-1.25x), so "
            "the speedup here reflects batching+pipelining overheads "
            "amortized, not the dispatch gap")
        payload["tpu_reference_context"] = {
            "file": "BENCH_r05 (round-5 on-chip capture)",
            "batch1_pairs_per_sec": 31.5,
            "batch128_pairs_per_sec": 98.7,
            "note": "labelled context from the committed TPU capture, "
                    "not a substitute measurement",
        }
    _emit(payload)


def _serving_failure(msg: str) -> None:
    _emit({"metric": SERVING_METRIC, "value": None, "unit": "x",
           "error": msg})


WIRE_METRIC = "serving_staged_bytes_ratio_f32_over_u8"


def wire_main(wire: str = "ab"):
    """``python bench.py serving --wire {u8,f32,ab}`` — wire-format
    byte benchmark (round 8).

    Measures what the host path actually memcpy's per request on each
    wire dtype: ``serving_staged_bytes`` is accumulated by the engine's
    staging arena at stack time (real traffic, tail-padding included),
    so the uint8 wire's advantage is a measured counter, not
    ``sizeof`` arithmetic. ``ab`` (the committed-artifact arm) runs
    both wires plus a MIXED-dtype pass on the same dual-dtype-warmed
    engine and records the f32/u8 staged-bytes-per-request ratio as
    the headline — the acceptance bar is >= 3x (the dtype alone gives
    4x; sub-max_batch tail padding dilutes per-request attribution on
    short runs, hence the margin). The mixed pass must trigger ZERO
    fresh XLA compiles — warmup pre-compiles both wire dtypes per
    bucket, so heterogeneous client dtypes never compile under load.

    The ``low_res`` response rides along: the same engine serves a
    block of 1/8-grid responses and the artifact records returned
    bytes per request for full vs low-res (the D2H + host-copy lever
    for throughput-over-fidelity clients). Same operating points and
    honesty clauses as ``serving_main``."""
    import jax
    import numpy as np

    from raft_tpu.evaluate import load_predictor
    from raft_tpu.serving import ServingConfig, ServingEngine, loadgen
    from raft_tpu.serving.metrics import CompileWatch

    platform = jax.devices()[0].platform
    ncores = os.cpu_count() or 1
    if platform == "tpu":
        shapes = [(436, 1024)]
        small, iters = False, ITERS
        max_batch, concurrency, n_requests = 32, 16, 256
        max_wait_ms = 5.0
    else:
        shapes = [(64, 96), (61, 93)]     # two raws, one padded bucket
        small, iters = True, 4
        max_batch, concurrency, n_requests = 8, 8, 48
        max_wait_ms = 4.0

    predictor = load_predictor("random", small=small, iters=iters)
    frames_u8 = loadgen.make_frames(shapes, per_shape=2, seed=0)
    frames_f32 = loadgen.make_frames(shapes, per_shape=2, seed=0,
                                     dtype=np.float32)
    refs_u8 = loadgen.batched_reference_flows(predictor, frames_u8,
                                              max_batch=max_batch)
    refs_f32 = loadgen.batched_reference_flows(predictor, frames_f32,
                                               max_batch=max_batch)

    engine = ServingEngine(predictor, ServingConfig(
        max_batch=max_batch, max_wait_ms=max_wait_ms,
        buckets=tuple(shapes), persistent_cache=True))
    t0 = time.perf_counter()
    warm = engine.warmup()
    warmup_s = round(time.perf_counter() - t0, 3)
    engine.start(warmup=False)

    arms = {"u8": (frames_u8, refs_u8), "f32": (frames_f32, refs_f32)}
    arm_names = ["u8", "f32"] if wire == "ab" else [wire]
    per_arm = {}
    try:
        for name in arm_names:
            frames, refs = arms[name]
            before = engine.metrics.snapshot()
            res = loadgen.run_load(engine, frames,
                                   n_requests=n_requests,
                                   concurrency=concurrency,
                                   references=refs)
            after = engine.metrics.snapshot()
            per_arm[name] = {
                "staged_bytes_per_request": round(
                    (after["serving_staged_bytes"]
                     - before["serving_staged_bytes"]) / n_requests, 1),
                "returned_bytes_per_request": round(
                    (after["serving_returned_bytes"]
                     - before["serving_returned_bytes"]) / n_requests,
                    1),
                "pairs_per_sec": round(res["throughput_rps"], 3),
                "latency_p50_ms": round(res["latency_ms"]["p50"], 2),
                "responses_bit_exact": res["ok"],
                "dropped": len(res["dropped"]),
                "mismatched": len(res["mismatched"]),
            }
        mixed_compiles = None
        low_res_bytes_per_request = None
        if wire == "ab":
            # Mixed-dtype traffic on the dual-dtype-warmed engine: the
            # zero-post-warmup-compile contract must hold across wires.
            mixed = frames_u8 + frames_f32
            mixed_refs = refs_u8 + refs_f32
            with CompileWatch() as watch:
                res_mix = loadgen.run_load(engine, mixed,
                                           n_requests=n_requests,
                                           concurrency=concurrency,
                                           references=mixed_refs)
            mixed_compiles = watch.compiles
            per_arm["mixed"] = {
                "responses_bit_exact": res_mix["ok"],
                "dropped": len(res_mix["dropped"]),
                "mismatched": len(res_mix["mismatched"]),
                "post_warmup_compiles": mixed_compiles,
            }
            # low_res: returned bytes per request at 1/8 grid.
            before = engine.metrics.snapshot()
            futs = [engine.submit(*frames_u8[i % len(frames_u8)],
                                  low_res=True)
                    for i in range(len(frames_u8) * 2)]
            for f in futs:
                f.result(300)
            after = engine.metrics.snapshot()
            low_res_bytes_per_request = round(
                (after["serving_returned_bytes"]
                 - before["serving_returned_bytes"]) / len(futs), 1)
    finally:
        engine.close()

    ratio = None
    if "u8" in per_arm and "f32" in per_arm:
        u8b = per_arm["u8"]["staged_bytes_per_request"]
        ratio = (round(per_arm["f32"]["staged_bytes_per_request"] / u8b,
                       3) if u8b else None)
    payload = {
        "metric": WIRE_METRIC,
        "value": ratio,
        "unit": "x",
        "platform": platform,
        "host_cores": ncores,
        "model": "raft-small" if small else "raft-large",
        "iters": iters,
        "shapes": [list(s) for s in shapes],
        "n_requests": n_requests,
        "concurrency": concurrency,
        "max_batch": max_batch,
        "max_wait_ms": max_wait_ms,
        "wire_arm": wire,
        "warmup_seconds": warmup_s,
        "warmup_compiles": int(sum(v["compiles"]
                                   for v in warm.values())),
        "per_wire": per_arm,
        "mixed_traffic_post_warmup_compiles": mixed_compiles,
        "low_res_returned_bytes_per_request": low_res_bytes_per_request,
        "host_stage_ms": engine.stages.summary(),
    }
    if platform != "tpu":
        payload["criterion_note"] = (
            "staged-bytes ratio is dtype arithmetic and holds on any "
            f"host; this {ncores}-core {platform} smoke point proves "
            "the counters, the bit-exactness, and the zero-compile "
            "mixed-traffic contract — the wall-clock win from 4x less "
            "host memcpy + H2D is a TPU-host phenomenon to capture "
            "on-chip")
    _emit(payload)


def _wire_failure(msg: str) -> None:
    _emit({"metric": WIRE_METRIC, "value": None, "unit": "x",
           "error": msg})


HIGHRES_METRIC = "highres_sharded_vs_unsharded_batch1_latency_speedup"


def highres_main(shards: int = 0):
    """``python bench.py serving --highres [--shards N]`` — multi-chip
    high-resolution serving benchmark (spatial sharding).

    The one workload single-chip batching can't help is a lone high-res
    request: it is latency-bound and unbatchable, and all-pairs
    correlation makes its cost quadratic in resolution. This mode
    measures what the spatially-sharded serving path buys for exactly
    that request: batch-1 latency of the sharded executable (rows split
    over the mesh's spatial axis, shard_map'd banded lookup) against
    the unsharded batch-1 executable at the SAME padded shape, plus a
    mixed-traffic section proving the sharded bucket serves from its
    own dispatch stream with zero post-warmup compiles while small-
    batch traffic flows beside it.

    On TPU the mesh spans the chips and the speedup is the headline;
    on the CPU smoke host the "devices" are forced host-platform
    threads sharing the same cores, so sharding adds collective
    overhead instead of compute — the artifact says so in
    ``criterion_note`` and carries ``smoke_operating_point`` rather
    than faking a win. What the smoke host DOES prove: bit-level
    response integrity, zero post-warmup compiles, and stream overlap.
    """
    import jax
    import numpy as np

    from raft_tpu.evaluate import load_predictor
    from raft_tpu.serving import ServingConfig, ServingEngine, loadgen
    from raft_tpu.serving.metrics import CompileWatch

    devices = jax.devices()
    platform = devices[0].platform
    n_dev = len(devices)
    if shards <= 0:
        shards = n_dev if platform == "tpu" else min(4, n_dev)
    if shards < 2 or n_dev < shards:
        _highres_failure(
            f"need >= 2 devices to shard (have {n_dev}, want {shards}); "
            "on CPU run with XLA_FLAGS=--xla_force_host_platform_"
            "device_count=8")
        return
    if platform == "tpu":
        highres, small_shapes = (436, 1024), [(184, 320)]
        small, iters, max_batch = False, ITERS, 8
        n_requests, concurrency = 64, 8
    else:
        highres, small_shapes = (96, 128), [(36, 60), (33, 57)]
        small, iters, max_batch = True, 2, 4
        n_requests, concurrency = 24, 6

    predictor = load_predictor("random", small=small, iters=iters)
    cfg = ServingConfig(
        max_batch=max_batch, max_wait_ms=3.0,
        buckets=tuple(small_shapes), sharded_buckets=(highres,),
        sharded_shards=shards,
        sharded_area_threshold=highres[0] * highres[1],
        persistent_cache=True)
    engine = ServingEngine(predictor, cfg)
    mesh = engine._sharded_mesh

    t0 = time.perf_counter()
    warm = engine.warmup()
    warmup = {"seconds": round(time.perf_counter() - t0, 3),
              "compiles": int(sum(v["compiles"] for v in warm.values())),
              "buckets": sorted(str(k) for k in warm)}

    # -- batch-1 latency: sharded vs unsharded at the same padded shape.
    # Direct dispatch (no queue) isolates the executable, which is what
    # the mesh changes; the queueing cost is identical for both.
    rng = np.random.default_rng(0)
    ph, pw = highres
    a = rng.uniform(0, 255, (1, ph, pw, 3)).astype(np.float32)
    b = rng.uniform(0, 255, (1, ph, pw, 3)).astype(np.float32)

    def _lat(fn, reps: int = REPS) -> dict:
        for _ in range(WARMUP):
            np.asarray(fn()[1])
        ts = []
        for _ in range(reps):
            t = time.perf_counter()
            np.asarray(fn()[1])
            ts.append((time.perf_counter() - t) * 1000.0)
        ts.sort()
        return {"p50_ms": round(ts[len(ts) // 2], 2),
                "min_ms": round(ts[0], 2),
                "max_ms": round(ts[-1], 2)}

    sharded_lat = _lat(
        lambda: predictor.sharded_dispatch(a, b, mesh=mesh))
    unsharded_lat = _lat(lambda: predictor.dispatch_batch(a, b))
    speedup = (unsharded_lat["p50_ms"] / sharded_lat["p50_ms"]
               if sharded_lat["p50_ms"] else None)

    # -- mixed traffic: highres + small-batch through ONE engine, zero
    # post-warmup compiles, per-bucket streams overlapping. References
    # per path: the batched executable for small frames, the sharded
    # executable for highres frames — each response must bit-match the
    # executable that contractually serves its bucket.
    small_frames = loadgen.make_frames(small_shapes, per_shape=2, seed=1)
    hi_frames = loadgen.make_frames([highres], per_shape=2, seed=2)
    frames = small_frames + hi_frames
    refs = loadgen.batched_reference_flows(
        frames=small_frames, predictor=predictor, max_batch=max_batch)
    for im1, im2 in hi_frames:
        out = predictor.sharded_dispatch(im1[None], im2[None], mesh=mesh)
        refs.append(np.asarray(out[1][0]))
    engine.start(warmup=False)
    try:
        with CompileWatch() as cw:
            res = loadgen.run_load(engine, frames, n_requests=n_requests,
                                   concurrency=concurrency,
                                   references=refs)
    finally:
        engine.close()

    payload = {
        "metric": HIGHRES_METRIC,
        "value": round(speedup, 3) if speedup else None,
        "unit": "x",
        "platform": platform,
        "devices": n_dev,
        "mesh": f"1x{shards}",
        "model": "raft-small" if small else "raft-large",
        "iters": iters,
        "highres_shape": list(highres),
        "small_shapes": [list(s) for s in small_shapes],
        "sharded_batch1_latency": sharded_lat,
        "unsharded_batch1_latency": unsharded_lat,
        "warmup": warmup,
        "mixed_traffic": {
            "n_requests": n_requests,
            "concurrency": concurrency,
            "completed": res["completed"],
            "dropped": len(res["dropped"]),
            "responses_bit_exact": res["ok"],
            "post_warmup_compiles": cw.compiles,
            "sharded_requests": int(
                engine.metrics.snapshot().get(
                    "serving_sharded_requests", 0)),
            "batch_histogram": {str(k): v for k, v in
                                sorted(res["batch_histogram"].items())},
            "throughput_rps": round(res["throughput_rps"], 3),
        },
    }
    if platform != "tpu":
        payload["smoke_operating_point"] = True
        payload["criterion_note"] = (
            "forced host-platform devices are threads on shared CPU "
            "cores: row-sharding adds halo/collective overhead without "
            "adding compute, so sharded latency >= unsharded here by "
            "construction. The CPU artifact proves correctness (bit-"
            "exact responses), zero post-warmup compiles, and stream "
            "overlap; the latency win is a multi-chip phenomenon")
        payload["tpu_expectation_note"] = (
            "on a TPU pod slice the mesh spans real chips: each holds "
            "1/d of every activation and of the (HW)^2 correlation "
            "volume, so batch-1 high-res latency scales down with the "
            "mesh — the round-5 8-way spatial-parallel capture is the "
            "trajectory reference; on-TPU serving capture is tracked "
            "as ROADMAP debt")
    _emit(payload)


def _highres_failure(msg: str) -> None:
    _emit({"metric": HIGHRES_METRIC, "value": None, "unit": "x",
           "error": msg})


STREAMING_METRIC = "streaming_warm_vs_stateless_pairs_per_sec_speedup"


def streaming_main():
    """``python bench.py streaming`` — session-aware streaming serving
    benchmark (warm start + encoder feature-map reuse).

    Drives N concurrent streaming sessions over temporally coherent
    synthetic streams and publishes their WARM steady-state throughput
    against the thing they replace: the same streams submitted as
    stateless ``(frame_k, frame_k+1)`` pairs through the same engine
    (every pair pays two fnet passes and full iterations). The frames,
    closed-loop client structure and timed-pair counts are identical
    between the two arms, so the ratio isolates exactly what sessions
    save: one encoder pass per warm frame plus the warm-start iteration
    discount. Emits ONE BENCH-compatible JSON line.

    Unlike the dispatch-gap serving benchmark this speedup is real on
    ANY platform — the saved encoder pass and GRU iterations are
    compute, not dispatch overhead — but CPU-smoke numbers still travel
    with their platform label and the accuracy context (warm-vs-cold
    flow drift per pair) so nobody mistakes a 1-core smoke point for a
    TPU capture.
    """
    import jax
    import numpy as np

    from raft_tpu.evaluate import load_predictor
    from raft_tpu.serving import ServingConfig, ServingEngine, loadgen
    from raft_tpu.serving.metrics import CompileWatch

    platform = jax.devices()[0].platform
    ncores = os.cpu_count() or 1
    if platform == "tpu":
        shape = (436, 1024)
        small, iters, warm_iters = False, ITERS, 6
        max_batch, n_streams, n_frames = 8, 16, 24
        max_wait_ms = 5.0
    else:
        shape = (64, 96)
        small, iters, warm_iters = True, 4, 2
        max_batch, n_streams, n_frames = 4, 6, 12
        max_wait_ms = 4.0

    predictor = load_predictor("random", small=small, iters=iters)
    cfg = ServingConfig(
        max_batch=max_batch, max_wait_ms=max_wait_ms,
        buckets=(shape,), warm_buckets=(shape,),
        warm_iters=warm_iters, persistent_cache=True)
    engine = ServingEngine(predictor, cfg)
    t0 = time.perf_counter()
    warm_stats = engine.warmup()
    warmup = {
        "seconds": round(time.perf_counter() - t0, 3),
        "compiles": int(sum(v["compiles"] for v in warm_stats.values()))}
    engine.start(warmup=False)
    try:
        with CompileWatch() as watch:
            base = loadgen.run_pair_stream_load(
                engine, n_streams, n_frames, shape=shape,
                collect_flows=True)
            stream = loadgen.run_stream_load(
                engine, n_streams, n_frames, shape=shape,
                collect_flows=True)
    finally:
        engine.close()

    # Accuracy context: per-pair drift of the warm session flow vs the
    # stateless flow over the SAME frames (pair 0 is the session's cold
    # pair — same executable family, listed separately), plus both
    # arms' EPE against the streams' constant ground-truth shift.
    warm_drift, cold_drift, epe_stream, epe_base = [], [], [], []
    for (gt, sflows), (_, bflows) in zip(stream["flows"], base["flows"]):
        for k, (sf, bf) in enumerate(zip(sflows, bflows)):
            d = float(np.mean(np.linalg.norm(sf - bf, axis=-1)))
            (cold_drift if k == 0 else warm_drift).append(d)
            epe_stream.append(
                float(np.mean(np.linalg.norm(sf - gt, axis=-1))))
            epe_base.append(
                float(np.mean(np.linalg.norm(bf - gt, axis=-1))))

    sessions = [rec["session"]
                for rec in stream["per_stream"].values()]
    hit_rates = [s["encoder_cache_hit_rate"] for s in sessions]
    expected_rate = (n_frames - 1) / n_frames
    speedup = (stream["pairs_per_s"] / base["pairs_per_s"]
               if base["pairs_per_s"] else None)
    lat = [rec["latency_ms"] for rec in stream["per_stream"].values()]
    payload = {
        "metric": STREAMING_METRIC,
        "value": round(speedup, 3) if speedup else None,
        "unit": "x",
        "platform": platform,
        "host_cores": ncores,
        "model": "raft-small" if small else "raft-large",
        "iters": iters,
        "warm_iters": warm_iters,
        "shape": list(shape),
        "n_streams": n_streams,
        "n_frames_per_stream": n_frames,
        "max_batch": max_batch,
        "max_wait_ms": max_wait_ms,
        "warmup": warmup,
        "streaming_pairs_per_sec": round(stream["pairs_per_s"], 3),
        "stateless_pairs_per_sec": round(base["pairs_per_s"], 3),
        "steady_pairs_per_arm": stream["steady_pairs"],
        "dropped": stream["dropped"] + base["dropped"],
        "per_stream_latency_p50_ms": round(
            float(np.median([l["p50"] for l in lat])), 2),
        "per_stream_latency_p99_ms": round(
            float(max(l["p99"] for l in lat)), 2),
        "encoder_cache_hit_rate_min": round(min(hit_rates), 4),
        "encoder_cache_hit_rate_expected": round(expected_rate, 4),
        "warm_pairs_total": sum(s["warm_pairs"] for s in sessions),
        "cold_pairs_total": sum(s["cold_pairs"] for s in sessions),
        "post_warmup_compiles": watch.compiles,
        "warm_vs_stateless_flow_drift_epe": {
            "warm_mean": round(float(np.mean(warm_drift)), 4),
            "warm_max": round(float(np.max(warm_drift)), 4),
            "cold_pair_mean": round(float(np.mean(cold_drift)), 4),
        },
        "epe_vs_gt": {
            "streaming_mean": round(float(np.mean(epe_stream)), 4),
            "stateless_mean": round(float(np.mean(epe_base)), 4),
        },
    }
    if platform != "tpu":
        # Honesty clause: this is a real compute saving (not a dispatch
        # artifact), so the ≥1.3x criterion IS meaningful on CPU — but
        # the absolute pairs/s and the random-weight EPE context are
        # smoke numbers, not a TPU capture, and say so.
        payload["criterion_note"] = (
            "warm speedup comes from skipping one fnet pass per frame "
            f"and running {warm_iters} vs {iters} GRU iterations — a "
            "compute saving measurable on this "
            f"{ncores}-core {platform} smoke host; absolute pairs/s "
            "and the random-weight EPE context are NOT TPU numbers")
        payload["tpu_reference_context"] = {
            "file": "BENCH_r05 (round-5 on-chip capture)",
            "note": "no committed TPU streaming capture yet; stateless "
                    "serving context only — labelled context, not a "
                    "substitute measurement",
        }
    _emit(payload)


def _streaming_failure(msg: str) -> None:
    _emit({"metric": STREAMING_METRIC, "value": None, "unit": "x",
           "error": msg})


CONTBATCH_METRIC = "contbatch_vs_bucketed_mixed_iters_throughput_speedup"


def contbatch_main(arm: str = "ab"):
    """``python bench.py serving --contbatch {ab,on,off}`` — iteration-
    granular continuous batching benchmark (round 9, BENCH_r09).

    The workload is MIXED-iteration traffic: requests spread across the
    quality ladder (full / degraded levels) with early exit live, the
    shape brownout and per-request ``iters`` produce in production. The
    bucketed monolithic path fragments that traffic into one
    ``(H, W, lvl, wire)`` bucket per level — each dispatching the full
    ``max_batch``-slot executable around whatever handful of requests
    its lane collected, tail-padding the rest — while the continuous
    scheduler packs every level into ONE slot table, retires each slot
    the step its request's budget (or early-exit convergence) lands,
    and refills it from the queue on the next step.

    ``ab`` (the committed-artifact arm) runs both paths over identical
    frames/levels/references and publishes the continuous/bucketed
    throughput ratio as the headline (acceptance bar: >= 1.3x on this
    traffic). ``on``/``off`` run a single arm for debugging. Every
    response in both arms is graded against per-level monolithic
    references honoring each arm's early-exit contract (see the
    reference builder below) — bit-exact on the bucketed arm, <= 1e-4
    EPE on the continuous arm (same math, differently fused
    executables) — and both arms must serve with ZERO post-warmup
    compiles. Same operating points and honesty clauses as
    ``serving_main``."""
    import jax
    import numpy as np

    from raft_tpu.evaluate import load_predictor
    from raft_tpu.serving import ServingConfig, ServingEngine, loadgen
    from raft_tpu.serving.metrics import CompileWatch
    from raft_tpu.utils.padder import InputPadder

    platform = jax.devices()[0].platform
    ncores = os.cpu_count() or 1
    if platform == "tpu":
        shapes = [(436, 1024)]
        small, full_iters = False, ITERS
        max_batch, concurrency, n_requests = 32, 16, 256
        max_wait_ms = 5.0
        ladder = (8, 4)
    else:
        shapes = [(64, 96), (61, 93)]     # two raws, one padded bucket
        small, full_iters = True, 4
        max_batch, concurrency, n_requests = 8, 8, 48
        max_wait_ms = 4.0
        ladder = (2, 1)
    levels = [full_iters, *ladder]

    predictor = load_predictor("random", small=small, iters=full_iters)
    # Early exit live: loose tolerance so a fraction of requests
    # converge before their budget — the continuous scheduler turns
    # those freed slot-iterations into admissions; references below are
    # computed with the SAME setting so they remain the served truth.
    predictor.early_exit = (5.0, 1)
    frames = loadgen.make_frames(shapes, per_shape=2, seed=0,
                                 dtype=np.float32)

    def _refs_at(lvl, legacy: bool):
        refs = []
        for im1, im2 in frames:
            padder = InputPadder(im1.shape, mode="sintel", factor=8)
            p1, p2 = padder.pad(im1, im2)
            i1 = np.repeat(p1[None], max_batch, axis=0)
            i2 = np.repeat(p2[None], max_batch, axis=0)
            out = (predictor.dispatch_batch(i1, i2) if legacy
                   else predictor.dispatch_batch(i1, i2, iters=lvl))
            refs.append(padder.unpad(np.asarray(out[1])[0]))
        return refs

    # Per-ARM references, because the two paths make different (both
    # correct) early-exit promises at full quality: the bucketed
    # engine serves full-quality requests through the legacy no-iters
    # executable, where early exit does not apply; the continuous
    # scheduler applies per-slot early exit to EVERY request — that
    # wall-clock is precisely what this benchmark measures. Ladder
    # levels go through the early-exit-enabled iters executables on
    # both paths.
    refs_cont = {lvl: _refs_at(lvl, legacy=False) for lvl in levels}
    refs_mono = dict(refs_cont)
    refs_mono[full_iters] = _refs_at(full_iters, legacy=True)

    def _run_arm(continuous: bool) -> dict:
        cfg = ServingConfig(
            max_batch=max_batch, max_wait_ms=max_wait_ms,
            buckets=tuple(shapes), iters_ladder=ladder,
            continuous=continuous, contbatch_steps=1,
            persistent_cache=True)
        engine = ServingEngine(predictor, cfg)
        t0 = time.perf_counter()
        warm = engine.warmup()
        warm_s = round(time.perf_counter() - t0, 3)
        engine.start(warmup=False)
        try:
            with CompileWatch() as watch:
                res = loadgen.run_mixed_iters_load(
                    engine, frames, n_requests=n_requests,
                    levels=levels,
                    refs_by_iters=(refs_cont if continuous
                                   else refs_mono),
                    concurrency=concurrency)
        finally:
            engine.close()
        snap = res["metrics"]
        rec = {
            "mixed_iters_pairs_per_sec": round(res["throughput_rps"], 3),
            "completed": res["completed"],
            "dropped": len(res["dropped"]),
            "mismatched": len(res["mismatched"]),
            "worst_epe_vs_monolithic": round(res["worst_epe"], 8),
            "post_warmup_compiles": watch.compiles,
            "warmup_seconds": warm_s,
            "warmup_compiles": int(sum(v["compiles"]
                                       for v in warm.values())),
            "latency_p50_ms": round(res["latency_ms"]["p50"], 2),
            "latency_p99_ms": round(res["latency_ms"]["p99"], 2),
            "level_counts": {str(k): v
                             for k, v in res["level_counts"].items()},
        }
        if continuous:
            rec["contbatch"] = {
                "slots": max_batch,
                "steps_per_dispatch": 1,
                "admits": int(snap["serving_contbatch_admits"]),
                "retires": int(snap["serving_contbatch_retires"]),
                "scheduler_steps": int(snap["serving_contbatch_steps"]),
                "mean_slot_occupancy": round(
                    snap["serving_contbatch_mean_occupancy"], 2),
                "freed_iters": int(snap["serving_contbatch_freed_iters"]),
                "early_exit_iters_saved": int(
                    snap["serving_early_exit_iters_saved"]),
            }
        return rec

    per_arm = {}
    if arm in ("ab", "off"):
        per_arm["bucketed"] = _run_arm(continuous=False)
    if arm in ("ab", "on"):
        per_arm["continuous"] = _run_arm(continuous=True)

    speedup = None
    if "continuous" in per_arm and "bucketed" in per_arm:
        base = per_arm["bucketed"]["mixed_iters_pairs_per_sec"]
        if base:
            speedup = round(
                per_arm["continuous"]["mixed_iters_pairs_per_sec"]
                / base, 3)
    payload = {
        "metric": CONTBATCH_METRIC,
        "value": speedup,
        "unit": "x",
        "platform": platform,
        "host_cores": ncores,
        "model": "raft-small" if small else "raft-large",
        "full_iters": full_iters,
        "iters_ladder": list(ladder),
        "levels": levels,
        "early_exit": list(predictor.early_exit),
        "shapes": [list(s) for s in shapes],
        "n_requests": n_requests,
        "concurrency": concurrency,
        "max_batch": max_batch,
        "max_wait_ms": max_wait_ms,
        "contbatch_arm": arm,
        "per_arm": per_arm,
    }
    if platform != "tpu":
        payload["smoke_operating_point"] = True
        payload["criterion_note"] = (
            "unlike the dispatch-gap serving headline, this ratio is "
            "utilization arithmetic and holds on any host: both arms "
            "run the same per-iteration math on the same "
            f"{ncores}-core {platform} host, and the win is dense slot "
            "occupancy vs per-level bucket fragmentation + tail "
            "padding (throughput scales with the mean-iters/max-iters "
            "ratio of the traffic). Absolute pairs/s is a smoke "
            "number; the on-TPU capture is tracked as ROADMAP debt")
    _emit(payload)


def _contbatch_failure(msg: str) -> None:
    _emit({"metric": CONTBATCH_METRIC, "value": None, "unit": "x",
           "error": msg})


GATEWAY_METRIC = "gateway_vs_inprocess_p50_latency_overhead_ms"


def gateway_main(arm: str = "ab"):
    """``python bench.py serving --gateway {ab,on,off}`` — socket-hop
    overhead of the multi-process serving tier (BENCH_gateway).

    Both arms run the SAME predictor, engine config, frames, and
    closed-loop load: the ``in_process`` arm submits straight to a
    :class:`~raft_tpu.serving.engine.ServingEngine` (the path every
    serving benchmark to date measured); the ``gateway`` arm puts that
    same engine behind a :class:`~raft_tpu.serving.worker.WorkerServer`
    socket in this process and routes through a
    :class:`~raft_tpu.serving.gateway.ServingGateway` over a file lease
    store — so the delta is exactly the network tier's toll (length-
    prefixed framing, the uint8 wire bytes over a local socket into the
    worker's staging arena, lease-routed dispatch) and not a different
    model, batcher, or host. The headline is client-observed p50
    latency through the gateway minus in-process p50, in ms (both from
    ``run_load``'s submit-to-result clock, the number a caller actually
    feels). ``on``/``off`` run a single arm for debugging.

    Honesty contract: every response in BOTH arms is checked bit-exact
    against same-executable references, and both arms must serve with
    ZERO post-warmup compiles — the gateway path rides the exact
    executables the in-process path warmed."""
    import dataclasses
    import tempfile

    import jax

    from raft_tpu.evaluate import load_predictor
    from raft_tpu.serving import ServingConfig, ServingEngine, loadgen
    from raft_tpu.serving.gateway import GatewayConfig, ServingGateway
    from raft_tpu.serving.metrics import CompileWatch
    from raft_tpu.serving.netproto import FileLeaseStore
    from raft_tpu.serving.worker import WorkerConfig, WorkerServer

    platform = jax.devices()[0].platform
    ncores = os.cpu_count() or 1
    if platform == "tpu":
        shapes = [(436, 1024)]
        small, iters = False, ITERS
        max_batch, concurrency, n_requests = 16, 16, 128
        max_wait_ms = 5.0
    else:
        shapes = [(64, 96), (61, 93)]     # two raws, one padded bucket
        small, iters = True, 2
        max_batch, concurrency, n_requests = 4, 8, 48
        max_wait_ms = 3.0

    predictor = load_predictor("random", small=small, iters=iters)
    frames = loadgen.make_frames(shapes, per_shape=2, seed=0)
    refs = loadgen.batched_reference_flows(frames=frames,
                                           predictor=predictor,
                                           max_batch=max_batch)
    cfg = ServingConfig(
        max_batch=max_batch, max_wait_ms=max_wait_ms,
        buckets=tuple(shapes), persistent_cache=True)

    def _arm_record(res, watch, warm_s) -> dict:
        # Single replica per arm, so per_replica has exactly one entry:
        # its client-observed (submit -> result) latency is the number
        # both arms are compared on.
        client = next(iter(res["per_replica"].values()))["latency_ms"]
        return {
            "completed": res["completed"],
            "dropped": len(res["dropped"]),
            "mismatched": len(res["mismatched"]),
            "p50_ms": round(client["p50"], 3),
            "p99_ms": round(client["p99"], 3),
            "throughput_rps": round(res["throughput_rps"], 3),
            "post_warmup_compiles": watch.compiles,
            "warmup_seconds": warm_s,
        }

    def _run_in_process() -> dict:
        engine = ServingEngine(predictor, cfg)
        t0 = time.perf_counter()
        engine.warmup()
        warm_s = round(time.perf_counter() - t0, 3)
        engine.start(warmup=False)
        try:
            with CompileWatch() as watch:
                res = loadgen.run_load(
                    engine, frames, n_requests=n_requests,
                    concurrency=concurrency, references=refs,
                    timeout=600.0)
        finally:
            engine.close()
        return _arm_record(res, watch, warm_s)

    def _run_gateway(lease_dir: str) -> dict:
        store = FileLeaseStore(lease_dir)
        engine = ServingEngine(predictor, dataclasses.replace(
            cfg, replica_id="w0"))
        server = WorkerServer(
            engine,
            WorkerConfig(worker_id="w0", lease_dir=lease_dir,
                         heartbeat_interval_s=0.2,
                         buckets=tuple(shapes), max_batch=max_batch,
                         max_wait_ms=max_wait_ms, step=0),
            lease_store=store)
        t0 = time.perf_counter()
        server.start(warmup=True)
        warm_s = round(time.perf_counter() - t0, 3)
        gw = ServingGateway(store, GatewayConfig(
            queue_timeout_ms=600_000, lease_ttl_s=2.0,
            poll_interval_s=0.1, dispatch_threads=concurrency,
            expected_step=0))
        try:
            gw.start()
            t_join = time.monotonic() + 120.0
            while not gw.live_workers():
                if time.monotonic() > t_join:
                    raise RuntimeError("worker never became routable")
                time.sleep(0.05)
            with CompileWatch() as watch:
                res = loadgen.run_load(
                    gw, frames, n_requests=n_requests,
                    concurrency=concurrency, references=refs,
                    timeout=600.0)
            lease = store.read_all().get("w0")
            lease_compiles = (lease.extra.get("post_warmup_compiles")
                              if lease is not None else None)
        finally:
            gw.close()
            server.stop()
        rec = _arm_record(res, watch, warm_s)
        rec["worker_lease_compiles"] = lease_compiles
        return rec

    per_arm = {}
    if arm in ("ab", "off"):
        per_arm["in_process"] = _run_in_process()
    if arm in ("ab", "on"):
        with tempfile.TemporaryDirectory() as lease_dir:
            per_arm["gateway"] = _run_gateway(lease_dir)

    overhead = None
    if "in_process" in per_arm and "gateway" in per_arm:
        overhead = round(per_arm["gateway"]["p50_ms"]
                         - per_arm["in_process"]["p50_ms"], 3)
    payload = {
        "metric": GATEWAY_METRIC,
        "value": overhead,
        "unit": "ms",
        "platform": platform,
        "host_cores": ncores,
        "model": "raft-small" if small else "raft-large",
        "iters": iters,
        "shapes": [list(s) for s in shapes],
        "n_requests": n_requests,
        "concurrency": concurrency,
        "max_batch": max_batch,
        "max_wait_ms": max_wait_ms,
        "gateway_arm": arm,
        "per_arm": per_arm,
    }
    if platform != "tpu":
        payload["smoke_operating_point"] = True
        payload["criterion_note"] = (
            "both arms run the same small-model executables on this "
            f"{ncores}-core {platform} host, so the p50 DELTA isolates "
            "the local-socket gateway hop (framing + wire bytes + "
            "lease routing) at a smoke operating point; absolute "
            "latencies are smoke numbers, and the flagship-shape "
            "on-TPU capture is tracked as ROADMAP debt")
    _emit(payload)


def _gateway_failure(msg: str) -> None:
    _emit({"metric": GATEWAY_METRIC, "value": None, "unit": "ms",
           "error": msg})


EDGE_METRIC = "edge_vs_inprocess_p50_latency_overhead_ms"


def edge_main(arm: str = "ab"):
    """``python bench.py serving --edge {ab,on,off}`` — the HTTP front
    door's toll on a client request (BENCH_edge).

    Both arms run the SAME predictor, engine config, frames, and
    closed-loop concurrency. The ``in_process`` arm submits straight to
    a :class:`~raft_tpu.serving.engine.ServingEngine`; the ``edge`` arm
    serves the same engine behind a :class:`~raft_tpu.serving.worker
    .WorkerServer` socket, routes through a :class:`~raft_tpu.serving
    .gateway.ServingGateway`, and fronts THAT with the
    :class:`~raft_tpu.serving.edge.EdgeServer` — real HTTP/1.1 clients
    (``submit_flow``) doing admission, header parsing, body staging and
    response encoding per request. The headline is client-observed p50
    through the full edge stack minus in-process p50, in ms — what
    putting the hardened front door (plus the gateway hop it sits on)
    in front of a request actually costs. ``on``/``off`` run one arm.

    Honesty contract: every response in BOTH arms is checked bit-exact
    against same-executable references, and both arms serve with ZERO
    post-warmup compiles."""
    import dataclasses
    import tempfile

    import jax
    import numpy as np

    from raft_tpu.evaluate import load_predictor
    from raft_tpu.serving import ServingConfig, ServingEngine, loadgen
    from raft_tpu.serving import edge as edge_mod
    from raft_tpu.serving.gateway import GatewayConfig, ServingGateway
    from raft_tpu.serving.metrics import CompileWatch, _percentile
    from raft_tpu.serving.netproto import FileLeaseStore
    from raft_tpu.serving.worker import WorkerConfig, WorkerServer

    platform = jax.devices()[0].platform
    ncores = os.cpu_count() or 1
    if platform == "tpu":
        shapes = [(436, 1024)]
        small, iters = False, ITERS
        max_batch, concurrency, n_requests = 16, 16, 128
        max_wait_ms = 5.0
    else:
        shapes = [(64, 96), (61, 93)]     # two raws, one padded bucket
        small, iters = True, 2
        max_batch, concurrency, n_requests = 4, 8, 48
        max_wait_ms = 3.0

    predictor = load_predictor("random", small=small, iters=iters)
    frames = loadgen.make_frames(shapes, per_shape=2, seed=0)
    refs = loadgen.batched_reference_flows(frames=frames,
                                           predictor=predictor,
                                           max_batch=max_batch)
    cfg = ServingConfig(
        max_batch=max_batch, max_wait_ms=max_wait_ms,
        buckets=tuple(shapes), persistent_cache=True)

    def _run_in_process() -> dict:
        engine = ServingEngine(predictor, cfg)
        t0 = time.perf_counter()
        engine.warmup()
        warm_s = round(time.perf_counter() - t0, 3)
        engine.start(warmup=False)
        try:
            with CompileWatch() as watch:
                res = loadgen.run_load(
                    engine, frames, n_requests=n_requests,
                    concurrency=concurrency, references=refs,
                    timeout=600.0)
        finally:
            engine.close()
        client = next(iter(res["per_replica"].values()))["latency_ms"]
        return {
            "completed": res["completed"],
            "dropped": len(res["dropped"]),
            "mismatched": len(res["mismatched"]),
            "p50_ms": round(client["p50"], 3),
            "p99_ms": round(client["p99"], 3),
            "throughput_rps": round(res["throughput_rps"], 3),
            "post_warmup_compiles": watch.compiles,
            "warmup_seconds": warm_s,
        }

    def _run_edge_http(addr) -> dict:
        """Closed-loop HTTP clients against the edge; latency is the
        full submit_flow round trip (the number a caller feels)."""
        lock = threading.Lock()
        it = iter(range(n_requests))
        lat_ms, mismatched, dropped = [], [], []

        def client():
            while True:
                with lock:
                    i = next(it, None)
                if i is None:
                    return
                fi = i % len(frames)
                im1, im2 = frames[fi]
                t0 = time.perf_counter()
                resp = edge_mod.submit_flow(addr, im1, im2,
                                            timeout=600.0)
                dt = (time.perf_counter() - t0) * 1e3
                if resp is None or resp.status != 200:
                    with lock:
                        dropped.append(i)
                    continue
                flow = edge_mod.decode_flow(resp)
                with lock:
                    lat_ms.append(dt)
                    if not np.array_equal(flow, refs[fi]):
                        mismatched.append(i)

        t0 = time.perf_counter()
        threads = [threading.Thread(target=client, daemon=True)
                   for _ in range(concurrency)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=900.0)
        wall = time.perf_counter() - t0
        return {
            "completed": len(lat_ms),
            "dropped": len(dropped),
            "mismatched": len(mismatched),
            "p50_ms": round(_percentile(lat_ms, 50), 3),
            "p99_ms": round(_percentile(lat_ms, 99), 3),
            "throughput_rps": round(len(lat_ms) / wall, 3),
        }

    def _run_edge(lease_dir: str) -> dict:
        store = FileLeaseStore(lease_dir)
        engine = ServingEngine(predictor, dataclasses.replace(
            cfg, replica_id="w0"))
        server = WorkerServer(
            engine,
            WorkerConfig(worker_id="w0", lease_dir=lease_dir,
                         heartbeat_interval_s=0.2,
                         buckets=tuple(shapes), max_batch=max_batch,
                         max_wait_ms=max_wait_ms, step=0),
            lease_store=store)
        t0 = time.perf_counter()
        server.start(warmup=True)
        warm_s = round(time.perf_counter() - t0, 3)
        gw = ServingGateway(store, GatewayConfig(
            queue_timeout_ms=600_000, lease_ttl_s=2.0,
            poll_interval_s=0.1, dispatch_threads=concurrency,
            expected_step=0))
        es = None
        try:
            gw.start()
            t_join = time.monotonic() + 120.0
            while not gw.live_workers():
                if time.monotonic() > t_join:
                    raise RuntimeError("worker never became routable")
                time.sleep(0.05)
            es = edge_mod.EdgeServer(gw).start_in_thread()
            with CompileWatch() as watch:
                rec = _run_edge_http(es.addr)
            lease = store.read_all().get("w0")
            rec["post_warmup_compiles"] = watch.compiles
            rec["warmup_seconds"] = warm_s
            rec["worker_lease_compiles"] = (
                lease.extra.get("post_warmup_compiles")
                if lease is not None else None)
        finally:
            if es is not None:
                es.shutdown_sync()     # closes the gateway too
            else:
                gw.close()
            server.stop()
        return rec

    per_arm = {}
    if arm in ("ab", "off"):
        per_arm["in_process"] = _run_in_process()
    if arm in ("ab", "on"):
        with tempfile.TemporaryDirectory() as lease_dir:
            per_arm["edge"] = _run_edge(lease_dir)

    overhead = None
    if "in_process" in per_arm and "edge" in per_arm:
        overhead = round(per_arm["edge"]["p50_ms"]
                         - per_arm["in_process"]["p50_ms"], 3)
    payload = {
        "metric": EDGE_METRIC,
        "value": overhead,
        "unit": "ms",
        "platform": platform,
        "host_cores": ncores,
        "model": "raft-small" if small else "raft-large",
        "iters": iters,
        "shapes": [list(s) for s in shapes],
        "n_requests": n_requests,
        "concurrency": concurrency,
        "max_batch": max_batch,
        "max_wait_ms": max_wait_ms,
        "edge_arm": arm,
        "per_arm": per_arm,
    }
    if platform != "tpu":
        payload["smoke_operating_point"] = True
        payload["criterion_note"] = (
            "both arms run the same small-model executables on this "
            f"{ncores}-core {platform} host, so the p50 DELTA isolates "
            "the HTTP front door stacked on the local-socket gateway "
            "hop (admission, header parse, body staging, response "
            "encoding) at a smoke operating point; absolute latencies "
            "are smoke numbers, and the flagship-shape on-TPU capture "
            "is tracked as ROADMAP debt")
    _emit(payload)


def _edge_failure(msg: str) -> None:
    _emit({"metric": EDGE_METRIC, "value": None, "unit": "ms",
           "error": msg})


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "streaming":
        try:
            streaming_main()
        except SystemExit:
            raise
        except BaseException as e:  # noqa: BLE001 — artifact must parse
            _streaming_failure(f"{type(e).__name__}: {e}")
        sys.exit(0)
    if len(sys.argv) > 1 and sys.argv[1] == "serving":
        if "--highres" in sys.argv[2:]:
            # Multi-chip path: on hosts without accelerators the mesh
            # comes from forced host-platform devices. Must be in the
            # environment before jax initializes its backend (first
            # jax.devices() call inside highres_main) — a no-op for the
            # CPU platform's count when already set, and irrelevant on
            # TPU where the real chips are the mesh.
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + " --xla_force_host_platform_device_count=8"
                ).strip()
            try:
                ap = argparse.ArgumentParser(
                    prog="bench.py serving --highres")
                ap.add_argument("--highres", action="store_true")
                ap.add_argument("--shards", type=int, default=0,
                                help="spatial mesh width (default: all "
                                     "devices on TPU, 4 on the CPU "
                                     "smoke host)")
                highres_main(
                    shards=ap.parse_args(sys.argv[2:]).shards)
            except SystemExit:
                raise
            except BaseException as e:  # noqa: BLE001
                _highres_failure(f"{type(e).__name__}: {e}")
            sys.exit(0)
        try:
            ap = argparse.ArgumentParser(prog="bench.py serving")
            ap.add_argument("--replicas", type=int, default=1,
                            help="serve through an N-replica fleet "
                                 "(default: 1, the single-engine "
                                 "trajectory point)")
            ap.add_argument("--wire", choices=("u8", "f32", "ab"),
                            default=None,
                            help="wire-format byte benchmark instead of "
                                 "the throughput benchmark: 'u8'/'f32' "
                                 "measure one wire dtype's staged bytes "
                                 "per request, 'ab' runs both plus a "
                                 "mixed-dtype zero-compile pass and "
                                 "records the f32/u8 ratio (the "
                                 "BENCH_r08 artifact)")
            ap.add_argument("--contbatch", choices=("ab", "on", "off"),
                            default=None,
                            help="iteration-granular continuous "
                                 "batching benchmark instead of the "
                                 "throughput benchmark: 'ab' runs "
                                 "mixed-iters traffic through both the "
                                 "continuous scheduler and the "
                                 "bucketed monolithic path and records "
                                 "the throughput ratio (the BENCH_r09 "
                                 "artifact); 'on'/'off' run one arm")
            ap.add_argument("--gateway", choices=("ab", "on", "off"),
                            default=None,
                            help="multi-process gateway-hop benchmark "
                                 "instead of the throughput benchmark: "
                                 "'ab' serves the same load in-process "
                                 "and through the socket gateway and "
                                 "records the p50 latency overhead "
                                 "(the BENCH_gateway artifact); "
                                 "'on'/'off' run one arm")
            ap.add_argument("--edge", choices=("ab", "on", "off"),
                            default=None,
                            help="HTTP front-door benchmark instead of "
                                 "the throughput benchmark: 'ab' serves "
                                 "the same load in-process and through "
                                 "the full edge -> gateway -> worker "
                                 "stack over real HTTP and records the "
                                 "p50 latency overhead (the BENCH_edge "
                                 "artifact); 'on'/'off' run one arm")
            ap.add_argument("--trace", action="store_true",
                            help="record a request-scoped trace of the "
                                 "benchmark run and ship its path as "
                                 "the artifact's trace_artifact key "
                                 "(Perfetto-loadable Chrome trace "
                                 "JSON)")
            args = ap.parse_args(sys.argv[2:])
            if args.edge is not None:
                try:
                    edge_main(arm=args.edge)
                except SystemExit:
                    raise
                except BaseException as e:  # noqa: BLE001
                    _edge_failure(f"{type(e).__name__}: {e}")
                sys.exit(0)
            if args.gateway is not None:
                try:
                    gateway_main(arm=args.gateway)
                except SystemExit:
                    raise
                except BaseException as e:  # noqa: BLE001
                    _gateway_failure(f"{type(e).__name__}: {e}")
                sys.exit(0)
            if args.contbatch is not None:
                try:
                    contbatch_main(arm=args.contbatch)
                except SystemExit:
                    raise
                except BaseException as e:  # noqa: BLE001
                    _contbatch_failure(f"{type(e).__name__}: {e}")
                sys.exit(0)
            if args.wire is not None:
                try:
                    wire_main(wire=args.wire)
                except SystemExit:
                    raise
                except BaseException as e:  # noqa: BLE001
                    _wire_failure(f"{type(e).__name__}: {e}")
                sys.exit(0)
            serving_main(replicas=args.replicas, trace=args.trace)
        except SystemExit:
            raise
        except BaseException as e:  # noqa: BLE001 — artifact must parse
            _serving_failure(f"{type(e).__name__}: {e}")
        sys.exit(0)
    try:
        ap = argparse.ArgumentParser(prog="bench.py")
        ap.add_argument("--gru", choices=("ab", "pallas", "xla"),
                        default="ab",
                        help="GRU-cell arm: 'ab' (default) measures the "
                             "headline under the ambient RAFT_GRU_PALLAS "
                             "and adds a forced pallas-vs-xla A/B pass; "
                             "'pallas'/'xla' force one dispatch for the "
                             "whole run (recorded in the payload)")
        ap.add_argument("--motion", choices=("ab", "pallas", "xla"),
                        default="ab",
                        help="motion-encoder arm (RAFT_MOTION_PALLAS), "
                             "same semantics as --gru: 'ab' (default) "
                             "adds a forced pallas-vs-xla A/B pass; "
                             "'pallas'/'xla' force one dispatch for the "
                             "whole run")
        ap.add_argument("--step", choices=("ab", "fused", "chained",
                                           "xla"),
                        default=None,
                        help="one-launch refine-iteration benchmark "
                             "instead of the headline: 'ab' measures "
                             "the fused single-launch step kernel "
                             "(RAFT_STEP_PALLAS) against the chained "
                             "motion+GRU launches and the pure-XLA "
                             "path and records the fused/chained "
                             "speedup plus each arm's handoff HBM "
                             "bytes (the BENCH_r10 artifact); "
                             "'fused'/'chained'/'xla' run one arm")
        args = ap.parse_args()
        if args.step is not None:
            try:
                step_main(arm=args.step)
            except SystemExit:
                raise
            except BaseException as e:  # noqa: BLE001
                _step_failure(f"{type(e).__name__}: {e}")
            sys.exit(0)
        if args.gru == "pallas":
            os.environ["RAFT_GRU_PALLAS"] = "1"
        elif args.gru == "xla":
            os.environ["RAFT_GRU_PALLAS"] = "0"
        if args.motion == "pallas":
            os.environ["RAFT_MOTION_PALLAS"] = "1"
        elif args.motion == "xla":
            os.environ["RAFT_MOTION_PALLAS"] = "0"
        main(gru=args.gru, motion=args.motion)
    except SystemExit:
        raise
    except BaseException as e:  # noqa: BLE001 — artifact must parse
        _emit_failure(f"{type(e).__name__}: {e}")
        sys.exit(0)
