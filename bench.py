"""Headline benchmark: Sintel image-pairs/sec/chip @ iters=12.

Runs the flagship canonical RAFT-large forward (test_mode, all-pairs
correlation) at Sintel resolution (436x1024 padded to 440x1024, the
``InputPadder`` pad-to-/8 shape) on the available accelerator and prints ONE
JSON line. ``vs_baseline`` is measured against the BASELINE.md north-star
denominator: the PyTorch reference on 1xV100 at the same setting, estimated
at 10 image-pairs/sec (RAFT paper reports ~10 fps at 1088x436 / 12 iters on
a 1080Ti-class GPU; BASELINE.md records no in-repo number, so the target
"≥4x vs V100" is normalized to this documented estimate).

Throughput is measured at batch=24 (the sweep's knee on v5e-1; the f32
all-pairs volume pyramid for 24 pairs is ~6 GB of the 16 GB HBM): per-chip
eval throughput is the metric, and batching frame pairs is how the
framework evaluates a 1000-frame Sintel pass on TPU; reps are dispatched
back-to-back and synced once so the device pipeline rate is measured, not
the host↔device round-trip latency of a lone request.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp


def _wait_for_backend(attempts: int = 4, delay_s: int = 120) -> None:
    """Survive transient accelerator-tunnel outages: backend init failures
    are retried by re-execing (jax caches a failed backend in-process)."""
    try:
        dev = jax.devices()[0]
        requested = (os.environ.get("JAX_PLATFORMS")
                     or str(jax.config.jax_platforms or ""))
        if dev.platform == "cpu" and not requested.startswith("cpu"):
            # Silent accelerator→CPU fallback would publish a wildly wrong
            # vs_baseline; make it loud (explicit cpu runs stay quiet).
            print("WARNING: no accelerator available — benchmarking on "
                  "CPU; vs_baseline is not comparable",
                  file=sys.stderr, flush=True)
        return
    except RuntimeError as e:
        tried = int(os.environ.get("RAFT_BENCH_INIT_TRY", "0"))
        if tried + 1 >= attempts:
            raise RuntimeError(
                f"accelerator backend unavailable after {attempts} "
                f"attempts: {e}") from e
        print(f"backend init failed (attempt {tried + 1}/{attempts}): {e}; "
              f"retrying in {delay_s}s", file=sys.stderr, flush=True)
        os.environ["RAFT_BENCH_INIT_TRY"] = str(tried + 1)
        time.sleep(delay_s)
        os.execv(sys.executable, [sys.executable] + sys.argv)

BASELINE_PAIRS_PER_SEC = 10.0   # PyTorch ref, 1xV100 (see module docstring)
H, W = 440, 1024                # Sintel 436x1024 after pad-to-/8
ITERS = 12
BATCH = 24
WARMUP = 2
REPS = 10


def main():
    _wait_for_backend()
    from raft_tpu.config import RAFTConfig
    from raft_tpu.models.raft import RAFT

    # TPU-first inference policy: bf16 encoders/update, f32 corr volume.
    platform = jax.devices()[0].platform
    cfg = RAFTConfig(iters=ITERS, mixed_precision=(platform == "tpu"))
    model = RAFT(cfg)
    rng = jax.random.PRNGKey(0)
    img1 = jax.random.uniform(rng, (1, H, W, 3), jnp.float32) * 255.0
    variables = model.init({"params": rng, "dropout": rng}, img1, img1,
                           iters=1)

    @jax.jit
    def fwd(i1, i2):
        return model.apply(variables, i1, i2, test_mode=True)[1]

    def throughput(batch: int) -> float:
        img = jnp.broadcast_to(img1, (batch, H, W, 3))
        for _ in range(WARMUP):
            fwd(img, img).block_until_ready()
        # Dispatch all reps, block once — measures device pipeline rate
        # (how eval/training actually stream batches), not the host↔device
        # round-trip latency of a lone request.
        t0 = time.perf_counter()
        outs = [fwd(img, img) for _ in range(REPS)]
        outs[-1].block_until_ready()
        return REPS * batch / (time.perf_counter() - t0)

    batch1 = throughput(1)
    pairs_per_sec = throughput(BATCH)
    print(json.dumps({
        "metric": "sintel_image_pairs_per_sec_per_chip_iters12",
        "value": round(pairs_per_sec, 3),
        "unit": "image-pairs/sec",
        "batch": BATCH,
        # single-pair throughput, apples-to-apples with the latency-bound
        # 10 pairs/sec V100 estimate the baseline is normalized to
        "value_batch1": round(batch1, 3),
        "vs_baseline": round(pairs_per_sec / BASELINE_PAIRS_PER_SEC, 3),
        "vs_baseline_batch1": round(batch1 / BASELINE_PAIRS_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
