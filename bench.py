"""Headline benchmark: Sintel image-pairs/sec/chip @ iters=12.

Runs the flagship canonical RAFT-large forward (test_mode, all-pairs
correlation) at Sintel resolution (436x1024 padded to 440x1024, the
``InputPadder`` pad-to-/8 shape) on the available accelerator and prints ONE
JSON line. ``vs_baseline`` is measured against the BASELINE.md north-star
denominator: the PyTorch reference on 1xV100 at the same setting, estimated
at 10 image-pairs/sec (RAFT paper reports ~10 fps at 1088x436 / 12 iters on
a 1080Ti-class GPU; BASELINE.md records no in-repo number, so the target
"≥4x vs V100" is normalized to this documented estimate).

Throughput is measured at batch=24 (the sweep's knee on v5e-1; the f32
all-pairs volume pyramid for 24 pairs is ~6 GB of the 16 GB HBM): per-chip
eval throughput is the metric, and batching frame pairs is how the
framework evaluates a 1000-frame Sintel pass on TPU; reps are dispatched
back-to-back and synced once (via a scalar host readback — more reliable
than ``block_until_ready`` through the accelerator tunnel) so the device
pipeline rate is measured, not the host↔device round-trip latency of a
lone request.

Failure contract: this script ALWAYS prints exactly one JSON line.  If the
accelerator tunnel is down, retries are bounded (``RAFT_BENCH_RETRY_S``,
default 15s x 4 attempts) and absolute wall-clock deadlines
(``RAFT_BENCH_DEADLINE_S`` for backend init, then
``RAFT_BENCH_TOTAL_DEADLINE_S`` as a total cap, both measured from the
FIRST exec across re-exec retries) are enforced by a watchdog thread —
backend init can hang inside C code far past any Python-level timeout —
so the driver artifact parses regardless of tunnel weather.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

METRIC = "sintel_image_pairs_per_sec_per_chip_iters12"
UNIT = "image-pairs/sec"
BASELINE_PAIRS_PER_SEC = 10.0   # PyTorch ref, 1xV100 (see module docstring)
H, W = 440, 1024                # Sintel 436x1024 after pad-to-/8
ITERS = 12
BATCH = 24
WARMUP = 2
REPS = 10
# sparse-family secondary metric: the fork's active training resolution
# (reference train_standard.sh:6: 352x480)
SPARSE_H, SPARSE_W, SPARSE_BATCH = 352, 480, 8


_EMIT_LOCK = threading.Lock()
_EMITTED = False


def _emit(payload: dict) -> bool:
    """Print the one-and-only JSON artifact line (first caller wins —
    the watchdog thread may race the success path).  The print happens
    INSIDE the lock so a losing watchdog blocks here until the winning
    line is fully flushed before it ``os._exit``s."""
    global _EMITTED
    with _EMIT_LOCK:
        if _EMITTED:
            return False
        _EMITTED = True
        print(json.dumps(payload), flush=True)
    return True


_PLATFORM: str | None = None   # set once the backend is up, for triage
_HEADLINE: dict | None = None  # completed headline numbers, survive a
                               # failure in the secondary metric


def _emit_failure(msg: str) -> None:
    """Terminal failure still yields one parseable JSON artifact line.
    If the headline measurement already completed (only a secondary
    metric was in flight), its numbers are published with the error
    attached rather than thrown away.  Includes the platform when known
    so a CPU-fallback timeout is not misread as a tunnel hang."""
    payload = dict(_HEADLINE) if _HEADLINE is not None else {
        "metric": METRIC,
        "value": None,
        "unit": UNIT,
        "vs_baseline": None,
    }
    payload["error"] = msg
    if _PLATFORM is not None:
        payload.setdefault("platform", _PLATFORM)
    _emit(payload)


class _Watchdog:
    """Hard wall-clock deadline surviving re-exec retries.

    ``jax.devices()`` on a wedged tunnel can block inside
    ``xla_client.make_c_api_client`` for 10+ minutes, beyond any Python
    try/except — only a watchdog thread + ``os._exit`` reliably gets the
    JSON line out before the driver's own timeout (rc=124, no artifact).

    Two phases, BOTH anchored to the first-exec start time so the whole
    process fits inside the driver's kill window (round-1 evidence puts
    that window near 30 min): a tight init deadline
    (``RAFT_BENCH_DEADLINE_S``) while the backend comes up, then — via
    :meth:`rearm` once the backend is healthy — a total-wall cap
    (``RAFT_BENCH_TOTAL_DEADLINE_S``, default 1500s from first exec) for
    compile + measurement, so a tunnel death mid-run still emits the
    artifact before the driver's rc=124.
    """

    def __init__(self) -> None:
        deadline_s = float(os.environ.get("RAFT_BENCH_DEADLINE_S", "1200"))
        self._start = float(os.environ.setdefault("RAFT_BENCH_START",
                                                  str(time.time())))
        self._expiry = self._start + deadline_s
        self._reason = "backend-init"
        if time.time() >= self._expiry:
            _emit_failure(f"deadline {deadline_s:.0f}s exceeded "
                          f"before start")
            os._exit(0)
        threading.Thread(target=self._watch, daemon=True).start()

    def rearm(self, unbounded: bool = False) -> None:
        if unbounded:
            # Explicitly-requested CPU smoke runs are interactive, not
            # driver artifacts; full-size CPU compute takes hours and
            # must not be misreported as an accelerator hang.
            self._expiry = float("inf")
            return
        total_s = float(
            os.environ.get("RAFT_BENCH_TOTAL_DEADLINE_S", "1500"))
        self._expiry = self._start + total_s
        self._reason = "compute (total wall cap)"

    def _watch(self) -> None:
        while True:
            remaining = self._expiry - time.time()
            if remaining <= 0:
                _emit_failure(
                    f"{self._reason} deadline exceeded "
                    f"(accelerator hang?)")
                os._exit(0)
            time.sleep(min(remaining, 5.0))


def _wait_for_backend(attempts: int = 4) -> bool:
    """Survive transient accelerator-tunnel outages: backend init failures
    are retried by re-execing (jax caches a failed backend in-process).
    The retry budget (attempts x RAFT_BENCH_RETRY_S) is kept far below the
    driver's timeout; terminal failure exits via ``_emit_failure``.

    Returns True iff the run is an *explicitly requested* CPU run (local
    smoke) — the caller uses this to lift the watchdog's wall cap."""
    global _PLATFORM
    import jax

    delay_s = float(os.environ.get("RAFT_BENCH_RETRY_S", "15"))
    try:
        dev = jax.devices()[0]
    except Exception as e:  # backend-init failures vary in exception type
        tried = int(os.environ.get("RAFT_BENCH_INIT_TRY", "0"))
        if tried + 1 >= attempts:
            _emit_failure(
                f"accelerator backend unavailable after {attempts} "
                f"attempts: {e}")
            sys.exit(0)
        print(f"backend init failed (attempt {tried + 1}/{attempts}): {e}; "
              f"retrying in {delay_s:.0f}s", file=sys.stderr, flush=True)
        os.environ["RAFT_BENCH_INIT_TRY"] = str(tried + 1)
        time.sleep(delay_s)
        os.execv(sys.executable, [sys.executable] + sys.argv)
    _PLATFORM = dev.platform
    requested = (os.environ.get("JAX_PLATFORMS")
                 or str(getattr(jax.config, "jax_platforms", "") or ""))
    cpu_explicit = requested.startswith("cpu")
    if dev.platform == "cpu" and not cpu_explicit:
        # Silent accelerator→CPU fallback would publish a wildly wrong
        # vs_baseline; make it loud (explicit cpu runs stay quiet).
        print("WARNING: no accelerator available — benchmarking on "
              "CPU; vs_baseline is not comparable",
              file=sys.stderr, flush=True)
    os.environ.pop("RAFT_BENCH_INIT_TRY", None)
    return dev.platform == "cpu" and cpu_explicit


def main():
    watchdog = _Watchdog()
    cpu_smoke = _wait_for_backend()
    watchdog.rearm(unbounded=cpu_smoke)
    import jax
    import jax.numpy as jnp
    from raft_tpu.config import RAFTConfig
    from raft_tpu.models.raft import RAFT

    # TPU-first inference policy: bf16 encoders/update, f32 corr volume.
    platform = jax.devices()[0].platform
    cfg = RAFTConfig(iters=ITERS, mixed_precision=(platform == "tpu"))
    model = RAFT(cfg)
    rng = jax.random.PRNGKey(0)
    img1 = jax.random.uniform(rng, (1, H, W, 3), jnp.float32) * 255.0
    variables = model.init({"params": rng, "dropout": rng}, img1, img1,
                           iters=1)

    @jax.jit
    def fwd(i1, i2):
        # Scalar-reduce the flow so syncing is a 4-byte host readback:
        # block_until_ready alone has returned early through the tunnel.
        flow_up = model.apply(variables, i1, i2, test_mode=True)[1]
        return flow_up, jnp.sum(flow_up)

    def throughput(batch: int, fwd_fn=None) -> float:
        fwd_fn = fwd_fn or fwd
        img = jnp.broadcast_to(img1, (batch, H, W, 3))
        for _ in range(WARMUP):
            float(fwd_fn(img, img)[1])
        # Dispatch all reps, sync once — measures device pipeline rate
        # (how eval/training actually stream batches), not the host↔device
        # round-trip latency of a lone request.
        # Keep only the newest output reference: execution is async, so
        # reps still pipeline back-to-back, but earlier ~86 MB flow
        # buffers are freed as they complete instead of 10 being pinned.
        t0 = time.perf_counter()
        for _ in range(REPS):
            out = fwd_fn(img, img)
        float(out[1])
        return REPS * batch / (time.perf_counter() - t0)

    global _HEADLINE
    batch1 = throughput(1)
    pairs_per_sec = throughput(BATCH)
    payload = {
        "metric": METRIC,
        "value": round(pairs_per_sec, 3),
        "unit": UNIT,
        "batch": BATCH,
        "platform": platform,
        # single-pair throughput, apples-to-apples with the latency-bound
        # 10 pairs/sec V100 estimate the baseline is normalized to
        "value_batch1": round(batch1, 3),
        "vs_baseline": round(pairs_per_sec / BASELINE_PAIRS_PER_SEC, 3),
        "vs_baseline_batch1": round(batch1 / BASELINE_PAIRS_PER_SEC, 3),
    }
    _HEADLINE = payload   # from here on a watchdog fire publishes these
    if platform == "cpu":
        # full-size secondaries on CPU take hours; they are TPU
        # measurements, not part of the CPU smoke contract
        payload["sparse_skipped"] = "cpu"
    else:
        try:
            # The HBM-traffic lever: identical to the headline config
            # except the volume pyramid is stored bf16 (accuracy budget
            # pinned by tests/test_golden.py::test_golden_bf16_corr_storage).
            # corr_dtype only changes storage, not parameters, so the
            # headline's variables are reused — no second eager init.
            cfg16 = RAFTConfig(iters=ITERS,
                               mixed_precision=(platform == "tpu"),
                               corr_dtype="bfloat16")
            model16 = RAFT(cfg16)

            @jax.jit
            def fwd16(i1, i2):
                flow_up = model16.apply(variables, i1, i2,
                                        test_mode=True)[1]
                return flow_up, jnp.sum(flow_up)

            payload["value_bf16_volume"] = round(
                throughput(BATCH, fwd16), 3)
        except Exception as e:
            payload["bf16_error"] = f"{type(e).__name__}: {e}"
        try:
            payload.update(_sparse_metrics())
        except Exception as e:  # secondary must never sink the artifact
            payload["sparse_error"] = f"{type(e).__name__}: {e}"
    _emit(payload)


def _sparse_metrics() -> dict:
    """Secondary metric: SparseRAFT forward throughput at the fork's
    active training resolution (352x480, ``train_standard.sh:6``).
    Same dispatch/sync discipline as the headline metric."""
    import jax
    import jax.numpy as jnp
    from raft_tpu.config import OursConfig
    from raft_tpu.models import SparseRAFT

    platform = jax.devices()[0].platform
    h, w, batch = SPARSE_H, SPARSE_W, SPARSE_BATCH
    model = SparseRAFT(OursConfig(mixed_precision=(platform == "tpu")))
    rng = jax.random.PRNGKey(0)
    img = jax.random.uniform(rng, (batch, h, w, 3), jnp.float32) * 255.0
    variables = model.init({"params": rng, "dropout": rng}, img, img)

    @jax.jit
    def fwd(i1, i2):
        flow_low, flow_up = model.apply(variables, i1, i2, test_mode=True)
        return jnp.sum(flow_up)

    for _ in range(WARMUP):
        float(fwd(img, img))
    t0 = time.perf_counter()
    for _ in range(REPS):
        out = fwd(img, img)
    float(out)
    rate = REPS * batch / (time.perf_counter() - t0)
    return {"sparse_forward_pairs_per_sec": round(rate, 3),
            "sparse_batch": batch, "sparse_resolution": [h, w]}


if __name__ == "__main__":
    try:
        main()
    except SystemExit:
        raise
    except BaseException as e:  # noqa: BLE001 — artifact must parse
        _emit_failure(f"{type(e).__name__}: {e}")
        sys.exit(0)
