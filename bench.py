"""Headline benchmark: Sintel image-pairs/sec/chip @ iters=12.

Runs the flagship canonical RAFT-large forward (test_mode, all-pairs
correlation) at Sintel resolution (436x1024 padded to 440x1024, the
``InputPadder`` pad-to-/8 shape) on the available accelerator and prints ONE
JSON line. ``vs_baseline`` is measured against the BASELINE.md north-star
denominator: the PyTorch reference on 1xV100 at the same setting, estimated
at 10 image-pairs/sec (RAFT paper reports ~10 fps at 1088x436 / 12 iters on
a 1080Ti-class GPU; BASELINE.md records no in-repo number, so the target
"≥4x vs V100" is normalized to this documented estimate).
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

BASELINE_PAIRS_PER_SEC = 10.0   # PyTorch ref, 1xV100 (see module docstring)
H, W = 440, 1024                # Sintel 436x1024 after pad-to-/8
ITERS = 12
WARMUP = 2
REPS = 10


def main():
    from raft_tpu.config import RAFTConfig
    from raft_tpu.models.raft import RAFT

    # TPU-first inference policy: bf16 encoders/update, f32 corr volume.
    platform = jax.devices()[0].platform
    cfg = RAFTConfig(iters=ITERS, mixed_precision=(platform == "tpu"))
    model = RAFT(cfg)
    rng = jax.random.PRNGKey(0)
    img = jax.random.uniform(rng, (1, H, W, 3), jnp.float32) * 255.0
    variables = model.init({"params": rng, "dropout": rng}, img, img,
                           iters=1)

    @jax.jit
    def fwd(i1, i2):
        return model.apply(variables, i1, i2, test_mode=True)[1]

    for _ in range(WARMUP):
        fwd(img, img).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(REPS):
        fwd(img, img).block_until_ready()
    dt = time.perf_counter() - t0

    pairs_per_sec = REPS / dt
    print(json.dumps({
        "metric": "sintel_image_pairs_per_sec_per_chip_iters12",
        "value": round(pairs_per_sec, 3),
        "unit": "image-pairs/sec",
        "vs_baseline": round(pairs_per_sec / BASELINE_PAIRS_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
