#!/usr/bin/env python
"""One-shot TPU measurement sweep for the non-headline benchmarks.

Covers, in independent sections (each guarded so one failure doesn't sink
the rest; results appended per-section to ``TPU_EXTRAS.json``):

* ``sparse_train``  — SparseRAFT train-step timing at the fork's active
  resolution (352x480, ``train_standard.sh:6``), batch swept.
* ``kitti_eval``    — canonical RAFT eval forward at KITTI resolution
  (1242x375 → padded 1248x384, ``BASELINE.json`` configs[4]) in mixed
  precision, all-pairs vs ``alternate_corr``, with peak-HBM telemetry.
* ``batch1``        — single-pair latency breakdown (the bench's
  batch-1 gap): plain batch 1 vs a double-buffered batch 2.
* ``msda_dense``    — one ``DeformableTransformerEncoderLayer`` at dense
  HW-token scale (the gather-bound path flagged in VERDICT r1 #10).

Run alone on the TPU host (the tunnel serializes processes):

    python scripts/tpu_extras_bench.py [section ...]

Timing uses a scalar host readback after every measured region —
``block_until_ready`` alone has returned early through the tunnel.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp

OUT_PATH = "TPU_EXTRAS.json"
WARMUP, REPS = 2, 10


def _sync(x) -> float:
    return float(jnp.sum(x) if x.ndim else x)


def _time(fn, *args, reps: int = REPS) -> float:
    """Mean seconds per call; dispatch back-to-back, readback once."""
    for _ in range(WARMUP):
        _sync(fn(*args))
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    _sync(out)
    return (time.perf_counter() - t0) / reps


def _peak_hbm_gb() -> float:
    stats = jax.devices()[0].memory_stats() or {}
    return round(stats.get("peak_bytes_in_use", 0) / 2 ** 30, 3)


def sparse_train() -> dict:
    """SparseRAFT forward AND train-step rates at 352x480."""
    from raft_tpu.config import OursConfig, TrainConfig
    from raft_tpu.models import SparseRAFT
    from raft_tpu.parallel import create_train_state, make_train_step

    H, W = 352, 480
    out = {"resolution": [H, W]}
    for batch in (2, 4, 8):
        tcfg = TrainConfig(model_family="sparse", batch_size=batch,
                           image_size=(H, W), iters=6, sparse_lambda=0.1)
        model = SparseRAFT(OursConfig(mixed_precision=True))
        rng = jax.random.PRNGKey(0)
        state = create_train_state(rng, model, tcfg, (H, W))
        step_fn = make_train_step(tcfg, donate=False)
        b = {"image1": jnp.ones((batch, H, W, 3)) * 127.0,
             "image2": jnp.ones((batch, H, W, 3)) * 127.0,
             "flow": jnp.zeros((batch, H, W, 2)),
             "valid": jnp.ones((batch, H, W))}

        def step(state_in):
            s2, metrics = step_fn(state_in, b, rng)
            return metrics["loss"]

        dt = _time(step, state, reps=5)
        out[f"train_step_ms_b{batch}"] = round(dt * 1e3, 2)
        out[f"train_samples_per_sec_b{batch}"] = round(batch / dt, 2)
        out[f"peak_hbm_gb_b{batch}"] = _peak_hbm_gb()
    return out


def kitti_eval() -> dict:
    """Canonical RAFT at KITTI 1242x375 (padded 1248x384), iters=24,
    mixed precision: all-pairs vs the on-demand Pallas path."""
    from raft_tpu.config import RAFTConfig
    from raft_tpu.models.raft import RAFT

    H, W = 384, 1248            # InputPadder kitti mode output
    out = {"resolution": [H, W], "iters": 24}
    rng = jax.random.PRNGKey(0)
    img = jax.random.uniform(rng, (1, H, W, 3), jnp.float32) * 255.0
    for name, alt in (("all_pairs", False), ("alternate_corr", True)):
        cfg = RAFTConfig(iters=24, mixed_precision=True,
                         alternate_corr=alt)
        model = RAFT(cfg)
        variables = model.init({"params": rng, "dropout": rng}, img, img,
                               iters=1)

        @jax.jit
        def fwd(i1, i2):
            return jnp.sum(model.apply(variables, i1, i2,
                                       test_mode=True)[1])

        dt = _time(fwd, img, img)
        out[f"{name}_ms"] = round(dt * 1e3, 2)
        out[f"{name}_pairs_per_sec"] = round(1.0 / dt, 2)
        out[f"{name}_peak_hbm_gb"] = _peak_hbm_gb()
    return out


def batch1() -> dict:
    """The batch-1 latency question (VERDICT r1 #9): is a doubled batch
    free (pipeline slack) or proportional (compute-bound)?"""
    from raft_tpu.config import RAFTConfig
    from raft_tpu.models.raft import RAFT

    H, W = 440, 1024
    out = {"resolution": [H, W], "iters": 12}
    rng = jax.random.PRNGKey(0)
    cfg = RAFTConfig(iters=12, mixed_precision=True)
    model = RAFT(cfg)
    img1 = jax.random.uniform(rng, (1, H, W, 3), jnp.float32) * 255.0
    variables = model.init({"params": rng, "dropout": rng}, img1, img1,
                           iters=1)

    @jax.jit
    def fwd(i1, i2):
        return jnp.sum(model.apply(variables, i1, i2, test_mode=True)[1])

    for batch in (1, 2, 3, 4):
        img = jnp.broadcast_to(img1, (batch, H, W, 3))
        dt = _time(fwd, img, img)
        out[f"ms_b{batch}"] = round(dt * 1e3, 2)
        out[f"pairs_per_sec_b{batch}"] = round(batch / dt, 2)
    # sequential-pair rate a latency-bound client actually sees at b=1,
    # vs streaming two pairs as one batch=2 (the double-buffer lever)
    return out


def msda_dense() -> dict:
    """DeformableTransformerEncoderLayer at dense HW-token scale
    (sparse-family stride-8 grid of the fork's training res)."""
    from raft_tpu.models.deformable import \
        DeformableTransformerEncoderLayer, DeformableTransformerEncoder

    out = {}
    for (h, w) in ((44, 60), (88, 120)):
        d_model = 128
        tokens = h * w
        layer = DeformableTransformerEncoderLayer(
            d_model=d_model, d_ffn=d_model * 4, dropout=0.0,
            activation="gelu", n_levels=1, n_heads=8, n_points=4)
        rng = jax.random.PRNGKey(0)
        src = jax.random.normal(rng, (1, tokens, d_model))
        ref = DeformableTransformerEncoder.get_reference_points([(h, w)])
        ref = jnp.broadcast_to(ref, (1, tokens, 1, 2))
        variables = layer.init({"params": rng}, src, None, ref, [(h, w)])

        @jax.jit
        def fwd(s):
            return jnp.sum(layer.apply(variables, s, None, ref, [(h, w)]))

        dt = _time(fwd, src)
        out[f"tokens_{tokens}_ms"] = round(dt * 1e3, 3)
    return out


SECTIONS = {"sparse_train": sparse_train, "kitti_eval": kitti_eval,
            "batch1": batch1, "msda_dense": msda_dense}


def main(argv):
    names = argv or list(SECTIONS)
    print("devices:", jax.devices(), flush=True)
    results = {}
    try:
        with open(OUT_PATH) as f:
            results = json.load(f)
    except Exception:
        pass
    for name in names:
        t0 = time.time()
        try:
            results[name] = SECTIONS[name]()
            results[name]["wall_s"] = round(time.time() - t0, 1)
            print(f"{name}: {json.dumps(results[name])}", flush=True)
        except Exception as e:
            results[name] = {"error": f"{type(e).__name__}: {e}"}
            print(f"{name}: FAILED {e}", flush=True)
        with open(OUT_PATH, "w") as f:
            json.dump(results, f, indent=1)
    print("wrote", OUT_PATH)


if __name__ == "__main__":
    main(sys.argv[1:])
