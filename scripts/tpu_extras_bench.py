#!/usr/bin/env python
"""One-shot TPU measurement sweep for the non-headline benchmarks.

Covers, in independent sections (each guarded so one failure doesn't sink
the rest; results appended per-section to ``TPU_EXTRAS.json``):

* ``sparse_train``  — SparseRAFT train-step timing at the fork's active
  resolution (352x480, ``train_standard.sh:6``), batch swept.
* ``raft_train``    — canonical RAFT train-step timing at the original
  chairs-stage resolution (368x496, ``train_mixed.sh:3``), batch swept.
* ``kitti_eval``    — canonical RAFT eval forward at KITTI resolution
  (1242x375 → padded 1248x384, ``BASELINE.json`` configs[4]) in mixed
  precision, all-pairs vs ``alternate_corr``, with per-program
  compiled-footprint telemetry.
* ``volume_memory`` — compiled HBM footprints (no execution) for the
  two correlation regimes at a volume-dominated point (Sintel, batch 4),
  where the on-demand path's memory advantage is visible.
* ``batch1``        — single-pair latency breakdown (the bench's
  batch-1 gap): batch sweep 1-4. Round-2 result: per-pair cost is flat
  b1→b3 and only falls at b4, i.e. the gap is small-tile MXU/VPU
  utilization, not host latency (see BASELINE.md).
* ``msda_dense``    — one ``DeformableTransformerEncoderLayer`` at dense
  HW-token scale (the gather-bound path flagged in VERDICT r1 #10),
  jnp vs Pallas backends.
* ``encoder_family`` — end-to-end ours_07-lineage forward (SparseRAFT
  with active encoder stacks), MSDA auto-Pallas vs forced gather path.
* ``msda_threshold`` — raw-op backend crossover across the dense-query
  dispatch boundary (query-count sweep, fresh jit per arm).
* ``golden_on_chip`` — golden parity EPEs measured on the chip for the
  all-pairs / banded-alternate / mixed-precision-policy arms (the CPU
  suite only runs the Pallas kernel in interpreter mode).

Run alone on the TPU host (the tunnel serializes processes):

    python scripts/tpu_extras_bench.py [section ...]

Timing uses a scalar host readback after every measured region —
``block_until_ready`` alone has returned early through the tunnel.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Same persistent compilation cache as bench.py — warm re-runs inside a
# tunnel window spend seconds, not minutes, compiling.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".jax_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")

import jax

# The axon plugin pins jax_platforms in jax.config at interpreter
# startup, overriding the env var; honor an explicit JAX_PLATFORMS so
# sections can be smoke-run on CPU (see tests/conftest.py).
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp

OUT_PATH = "TPU_EXTRAS.json"
WARMUP, REPS = 2, 10


def _sync(x) -> float:
    return float(jnp.sum(x) if x.ndim else x)


def _time(fn, *args, reps: int = REPS) -> float:
    """Mean seconds per call; dispatch back-to-back, readback once."""
    for _ in range(WARMUP):
        _sync(fn(*args))
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    _sync(out)
    return (time.perf_counter() - t0) / reps


def _compile(jitted, *args):
    """One AOT compile used for BOTH timing and footprint, so nothing is
    compiled twice and per-program numbers aren't polluted by the
    process-lifetime ``memory_stats()`` high-water mark (which is also
    simply unavailable through the accelerator tunnel)."""
    return jitted.lower(*args).compile()


def _hbm_gb(compiled) -> float:
    """Peak-HBM estimate from XLA's own buffer assignment."""
    try:
        ma = compiled.memory_analysis()
        total = (ma.temp_size_in_bytes + ma.argument_size_in_bytes +
                 ma.output_size_in_bytes)
        return round(total / 2 ** 30, 3)
    except Exception:
        return 0.0


def _train_rates(make_model, tcfg_kwargs, H, W, batches) -> dict:
    """Train-step timing sweep shared by the raft_train / sparse_train
    sections: state + jitted step per batch size, timed with the scalar
    readback, peak HBM from runtime telemetry or XLA buffer assignment."""
    from raft_tpu.config import TrainConfig
    from raft_tpu.parallel import create_train_state, make_train_step

    out = {"resolution": [H, W]}
    for batch in batches:
        tcfg = TrainConfig(batch_size=batch, image_size=(H, W),
                           **tcfg_kwargs)
        model = make_model()
        rng = jax.random.PRNGKey(0)
        state = create_train_state(rng, model, tcfg, (H, W))
        step_fn = make_train_step(tcfg, donate=False)
        b = {"image1": jnp.ones((batch, H, W, 3)) * 127.0,
             "image2": jnp.ones((batch, H, W, 3)) * 127.0,
             "flow": jnp.zeros((batch, H, W, 2)),
             "valid": jnp.ones((batch, H, W))}

        # Compile the FULL train step once (lowering a loss-only wrapper
        # would let XLA DCE the backward + optimizer and fake both the
        # timing and the footprint).
        compiled = _compile(step_fn, state, b, rng)

        def step(state_in):
            s2, metrics = compiled(state_in, b, rng)
            return metrics["loss"]

        dt = _time(step, state, reps=5)
        out[f"train_step_ms_b{batch}"] = round(dt * 1e3, 2)
        out[f"train_samples_per_sec_b{batch}"] = round(batch / dt, 2)
        out[f"peak_hbm_gb_b{batch}"] = _hbm_gb(compiled)
    return out


def _alt_train_arm(out: dict, make_alt_model, tcfg_kwargs, H, W,
                   batches, name: str) -> None:
    """Banded-kernel training arm shared by sparse_train/raft_train:
    measure _train_rates on the on-demand model, merge under an
    ``alt_`` prefix, band-retry wrapped — the kernel's backward
    compiling is exactly what the retry ladder protects, and a failure
    must not discard the base arm's already-measured numbers."""
    def arm():
        alt = _train_rates(make_alt_model, tcfg_kwargs, H, W, batches)
        out.update({f"alt_{k}": v for k, v in alt.items()
                    if k != "resolution"})

    _run_with_band_retry(arm, out, name, banded=True)


def sparse_train() -> dict:
    """SparseRAFT train-step rates at the fork's active resolution
    (352x480, ``train_standard.sh:6``); the ``alt_`` arms run the
    on-demand correlation path (``OursConfig.alternate_corr`` — deletes
    the volume + avg-pool chain the round-4 b8 profile measured at
    ~17% of the step)."""
    from raft_tpu.config import OursConfig

    def make_model(alternate=False):
        from raft_tpu.models import SparseRAFT
        return SparseRAFT(OursConfig(mixed_precision=True,
                                     alternate_corr=alternate))

    out = _train_rates(
        make_model,
        dict(model_family="sparse", iters=6, sparse_lambda=0.1),
        352, 480, (2, 4, 8))
    _alt_train_arm(out, lambda: make_model(alternate=True),
                   dict(model_family="sparse", iters=6, sparse_lambda=0.1),
                   352, 480, (4, 8), "sparse_alt_train")
    return out


def raft_train() -> dict:
    """Canonical RAFT train-step rates at the original chairs-stage
    resolution (368x496, ``train_mixed.sh:3``), mixed precision; the
    ``alt_`` arms train through the on-demand banded kernel (backward
    proven on-chip by the sparse A/B) instead of the materialized
    volume — numerics-identical, f32 accumulation either way."""
    from raft_tpu.config import RAFTConfig

    def make_model(alternate=False):
        from raft_tpu.models.raft import RAFT
        return RAFT(RAFTConfig(iters=12, mixed_precision=True,
                               alternate_corr=alternate))

    out = _train_rates(make_model, dict(iters=12), 368, 496, (4, 8))
    _alt_train_arm(out, lambda: make_model(alternate=True),
                   dict(iters=12), 368, 496, (4, 8), "raft_alt_train")
    return out


def kitti_eval() -> dict:
    """Canonical RAFT at KITTI 1242x375 (padded 1248x384), iters=24,
    mixed precision: all-pairs vs the on-demand Pallas path."""
    from raft_tpu.config import RAFTConfig
    from raft_tpu.models.raft import RAFT

    H, W = 384, 1248            # InputPadder kitti mode output
    out = {"resolution": [H, W], "iters": 24}
    rng = jax.random.PRNGKey(0)
    img = jax.random.uniform(rng, (1, H, W, 3), jnp.float32) * 255.0
    # alternate_corr runs bf16 MXU operands by default under mixed
    # precision (corr_mxu_dtype="auto"); the f32-MXU arm isolates that
    # lever from the banding/fusion redesign.
    for name, alt, mxu in (("all_pairs", False, "auto"),
                           ("alternate_corr", True, "auto"),
                           ("alternate_corr_f32mxu", True, "float32")):
        cfg = RAFTConfig(iters=24, mixed_precision=True,
                         alternate_corr=alt, corr_mxu_dtype=mxu)
        model = RAFT(cfg)
        variables = model.init({"params": rng, "dropout": rng}, img, img,
                               iters=1)

        def run(model=model, variables=variables, name=name):
            def fwd(i1, i2):
                return jnp.sum(model.apply(variables, i1, i2,
                                           test_mode=True)[1])
            compiled = _compile(jax.jit(fwd), img, img)
            dt = _time(compiled, img, img)
            out[f"{name}_ms"] = round(dt * 1e3, 2)
            out[f"{name}_pairs_per_sec"] = round(1.0 / dt, 2)
            out[f"{name}_compiled_hbm_gb"] = _hbm_gb(compiled)

        _run_with_band_retry(run, out, name, banded=alt)
    return out


def _run_with_band_retry(run, out: dict, name: str, banded: bool) -> None:
    """Non-banded arms run directly; banded arms get the kernel module's
    self-healing retry (one shared audited implementation — see
    raft_tpu.ops.corr_pallas.run_with_band_retry)."""
    if not banded:
        run()
        return
    from raft_tpu.ops.corr_pallas import run_with_band_retry
    run_with_band_retry(run, out, name)


def volume_memory() -> dict:
    """Where the on-demand path's memory win actually shows: compiled
    footprints (XLA buffer assignment, no execution) for all-pairs vs
    alternate_corr at a volume-dominated operating point — Sintel
    440x1024, batch 4, the f32 volume pyramid alone is ~1.1 GB."""
    from raft_tpu.config import RAFTConfig
    from raft_tpu.models.raft import RAFT

    H, W, batch = 440, 1024, 4
    out = {"resolution": [H, W], "batch": batch, "iters": 12}
    rng = jax.random.PRNGKey(0)
    img = jax.random.uniform(rng, (batch, H, W, 3), jnp.float32) * 255.0
    for name, alt in (("all_pairs", False), ("alternate_corr", True)):
        cfg = RAFTConfig(iters=12, mixed_precision=True,
                         alternate_corr=alt)
        model = RAFT(cfg)
        variables = model.init({"params": rng, "dropout": rng},
                               img[:1], img[:1], iters=1)

        @jax.jit
        def fwd(i1, i2):
            return jnp.sum(model.apply(variables, i1, i2,
                                       test_mode=True)[1])

        out[f"{name}_compiled_hbm_gb"] = _hbm_gb(_compile(fwd, img, img))
    return out


def loader_train() -> dict:
    """End-to-end train rate WITH the real input pipeline (round 5,
    VERDICT r4 #3): synthetic-but-real-shaped .ppm/.flo files on disk,
    read+decoded+augmented through the actual loader
    (``fetch_dataloader``-equivalent construction) feeding the jitted
    canonical-RAFT train step at the chairs operating point. Compares
    the loader-fed steady state against the synthetic-tensor-fed rate
    of the SAME compiled step, and records the host's core count — the
    capacity model is per-core loader rate x cores vs device rate
    (LOADER_BENCH.json: ~14-18 samples/s/core; a 1-core host is
    loader-bound by construction, a >=4-core pod host is not)."""
    import shutil
    import tempfile

    from raft_tpu.config import RAFTConfig, TrainConfig
    from raft_tpu.models.raft import RAFT
    from raft_tpu.parallel import create_train_state, make_train_step
    from scripts.loader_bench import make_dataset, make_fixture

    H, W = 368, 496                      # chairs crop
    batch = 4
    out = {"resolution": [H, W], "batch": batch,
           "cpu_count": os.cpu_count()}
    root = tempfile.mkdtemp(prefix="loader_train_")
    try:
        make_fixture(root)
        ds = 20 * make_dataset(root)
        # the SAME loader-kind/worker resolution training uses
        # (select_loader: process pool on >=4-core hosts, thread
        # prefetcher on small hosts) so this measures the default path
        from raft_tpu.data.datasets import select_loader
        cls, workers = select_loader()
        out["loader_kind"] = cls.__name__
        out["loader_workers"] = workers
        loader = cls(ds, batch_size=batch, shuffle=True,
                     num_workers=workers, prefetch=4)

        tcfg = TrainConfig(batch_size=batch, image_size=(H, W),
                           num_steps=100, iters=12)
        model = RAFT(RAFTConfig(iters=12, mixed_precision=True,
                                alternate_corr=True))
        rng = jax.random.PRNGKey(0)
        state = create_train_state(rng, model, tcfg, (H, W))
        step_fn = make_train_step(tcfg, donate=False)

        it = iter(loader)
        b0 = next(it)
        b0 = {k: jnp.asarray(v) for k, v in b0.items()}
        compiled = _compile(step_fn, state, b0, rng)

        # synthetic-fed reference rate (device-bound ceiling)
        def synth(state_in):
            _, m = compiled(state_in, b0, rng)
            return m["loss"]
        dt = _time(synth, state, reps=5)
        out["synthetic_fed_samples_per_sec"] = round(batch / dt, 2)

        # loader-fed steady state: overlapped (loader prefetches while
        # the device steps), 20 steps after 3 warmup
        n_warm, n_meas = 3, 20
        k = 0
        t0 = None
        cur = state
        while k < n_warm + n_meas:
            try:
                nb = next(it)
            except StopIteration:
                it = iter(loader)
                continue
            nb = {kk: jnp.asarray(v) for kk, v in nb.items()}
            cur, metrics = compiled(cur, nb, rng)
            k += 1
            if k == n_warm:
                float(metrics["loss"])
                t0 = time.perf_counter()
        float(metrics["loss"])
        rate = n_meas * batch / (time.perf_counter() - t0)
        out["loader_fed_samples_per_sec"] = round(rate, 2)
        out["loader_efficiency"] = round(
            rate / out["synthetic_fed_samples_per_sec"], 3)
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return out


def batch1() -> dict:
    """The batch-1 latency question (VERDICT r1 #9): is a doubled batch
    free (pipeline slack) or proportional (compute-bound)?"""
    from raft_tpu.config import RAFTConfig
    from raft_tpu.models.raft import RAFT

    H, W = 440, 1024
    out = {"resolution": [H, W], "iters": 12}
    rng = jax.random.PRNGKey(0)
    cfg = RAFTConfig(iters=12, mixed_precision=True)
    model = RAFT(cfg)
    img1 = jax.random.uniform(rng, (1, H, W, 3), jnp.float32) * 255.0
    variables = model.init({"params": rng, "dropout": rng}, img1, img1,
                           iters=1)

    @jax.jit
    def fwd(i1, i2):
        return jnp.sum(model.apply(variables, i1, i2, test_mode=True)[1])

    for batch in (1, 2, 3, 4):
        img = jnp.broadcast_to(img1, (batch, H, W, 3))
        dt = _time(fwd, img, img)
        out[f"ms_b{batch}"] = round(dt * 1e3, 2)
        out[f"pairs_per_sec_b{batch}"] = round(batch / dt, 2)

    # banded-engine arm (round 5, VERDICT r4 #4): does the b2/b3
    # superlinear-cost anomaly reproduce on the on-demand kernel, or is
    # it a materialized-pipeline (volume/lookup layout) artifact?
    amodel = RAFT(RAFTConfig(iters=12, mixed_precision=True,
                             alternate_corr=True))

    def alt_arm(batch):
        afwd = jax.jit(lambda i1, i2: jnp.sum(
            amodel.apply(variables, i1, i2, test_mode=True)[1]))
        img = jnp.broadcast_to(img1, (batch, H, W, 3))
        dt = _time(afwd, img, img)
        out[f"alt_ms_b{batch}"] = round(dt * 1e3, 2)
        out[f"alt_pairs_per_sec_b{batch}"] = round(batch / dt, 2)

    from raft_tpu.ops.corr_pallas import run_with_band_retry
    for batch in (1, 2, 3, 4):
        if not run_with_band_retry(lambda b=batch: alt_arm(b), out,
                                   f"alt_b{batch}"):
            break
    return out


def msda_dense() -> dict:
    """DeformableTransformerEncoderLayer at dense HW-token scale
    (sparse-family stride-8 grid of the fork's training res): the
    gather-based jnp core vs the hat-matmul Pallas kernel
    (``raft_tpu/ops/msda_pallas.py``; ``backend`` dispatch)."""
    from raft_tpu.models.deformable import \
        DeformableTransformerEncoderLayer, DeformableTransformerEncoder

    out = {}
    for (h, w) in ((44, 60), (88, 120)):
        d_model = 128
        tokens = h * w
        for backend in ("jnp", "pallas"):
            layer = DeformableTransformerEncoderLayer(
                d_model=d_model, d_ffn=d_model * 4, dropout=0.0,
                activation="gelu", n_levels=1, n_heads=8, n_points=4,
                backend=backend)
            rng = jax.random.PRNGKey(0)
            src = jax.random.normal(rng, (1, tokens, d_model))
            ref = DeformableTransformerEncoder.get_reference_points(
                [(h, w)])
            ref = jnp.broadcast_to(ref, (1, tokens, 1, 2))
            variables = layer.init({"params": rng}, src, None, ref,
                                   [(h, w)])

            @jax.jit
            def fwd(s):
                return jnp.sum(layer.apply(variables, s, None, ref,
                                           [(h, w)]))

            dt = _time(fwd, src)
            out[f"tokens_{tokens}_{backend}_ms"] = round(dt * 1e3, 3)
    return out


def encoder_family() -> dict:
    """End-to-end forward of the ours_07-lineage model (SparseRAFT with
    active deformable encoder stacks — the dense-query regime) at the
    fork's training resolution, with the MSDA auto dispatch (Pallas on
    TPU) vs the gather path forced via the dispatch threshold."""
    from raft_tpu.config import OursConfig
    from raft_tpu.models import SparseRAFT
    from raft_tpu.ops import msda

    # The A/B below is only meaningful where the auto dispatch can pick
    # the kernel — assert rather than silently record jnp-vs-jnp.
    assert jax.default_backend() == "tpu", \
        "encoder_family compares MSDA backends; auto==pallas only on TPU"
    H, W, batch = 352, 480, 4
    out = {"resolution": [H, W], "batch": batch, "encoder_iterations": 2,
           "platform": jax.default_backend()}
    model = SparseRAFT(OursConfig(mixed_precision=True,
                                  encoder_iterations=2))
    rng = jax.random.PRNGKey(0)
    img = jax.random.uniform(rng, (batch, H, W, 3), jnp.float32) * 255.0
    variables = model.init({"params": rng, "dropout": rng}, img, img)

    saved = msda._PALLAS_MIN_QUERIES
    hlo_fingerprint = {}
    try:
        for name, threshold in (("auto_pallas", saved),
                                ("jnp", 10 ** 9)):
            msda._PALLAS_MIN_QUERIES = threshold

            # A FRESH jitted callable per arm: jax's tracing cache is
            # keyed on the function object, so re-lowering one shared
            # `fwd` would silently reuse the jaxpr traced under the
            # previous threshold and time the same program twice.
            def arm_fwd(i1, i2):
                return jnp.sum(model.apply(variables, i1, i2,
                                           test_mode=True)[1])

            compiled = _compile(jax.jit(arm_fwd), img, img)
            hlo_fingerprint[name] = hash(compiled.as_text())
            dt = _time(compiled, img, img)
            out[f"{name}_ms"] = round(dt * 1e3, 2)
            out[f"{name}_pairs_per_sec"] = round(batch / dt, 2)
    finally:
        msda._PALLAS_MIN_QUERIES = saved
    assert hlo_fingerprint["auto_pallas"] != hlo_fingerprint["jnp"], \
        "A/B arms compiled to identical programs — dispatch didn't switch"
    out["arms_compiled_distinct"] = True
    return out


def msda_threshold() -> dict:
    """Measure the MSDA backend crossover across the dispatch boundary
    (VERDICT r2 #9: ``_PALLAS_MIN_QUERIES = 512`` was picked, not
    measured — the round-2 crossover data points were 2640/10560 tokens
    only). Raw op timing, fresh jit per arm, dense-regime value map
    (stride-8 grid of the fork's training res, d_model=128, 8 heads)."""
    from raft_tpu.ops import msda
    from raft_tpu.ops.msda import ms_deform_attn

    h, w, m, d, p, L = 44, 60, 8, 16, 4, 1
    s = h * w
    shapes = ((h, w),)
    rng = jax.random.PRNGKey(0)
    value = jax.random.normal(rng, (1, s, m, d), jnp.float32)
    out = {"value_tokens": s, "heads": m, "head_dim": d,
           "current_threshold": msda._PALLAS_MIN_QUERIES}
    for lq in (128, 256, 512, 1024, 2048, s):
        loc = jax.random.uniform(jax.random.PRNGKey(lq),
                                 (1, lq, m, L, p, 2), jnp.float32)
        wts = jax.nn.softmax(
            jax.random.normal(jax.random.PRNGKey(lq + 1),
                              (1, lq, m, L, p)), axis=-1)
        for backend in ("jnp", "pallas"):
            def arm(v, l, a, _b=backend):
                return jnp.sum(ms_deform_attn(v, shapes, l, a, backend=_b))
            compiled = _compile(jax.jit(arm), value, loc, wts)
            dt = _time(compiled, value, loc, wts)
            out[f"lq{lq}_{backend}_us"] = round(dt * 1e6, 1)
    return out


def golden_on_chip() -> dict:
    """Hardware-accuracy validation of the round-3 kernel work: golden
    parity EPEs measured ON the chip (the CPU suite runs the Pallas
    kernel in interpreter mode only). Arms: all-pairs f32 and the banded
    Pallas alternate path (both vs the stored f32 torch outputs — expect
    float-noise, ~3e-6 on CPU), plus the mixed-precision policy arms
    (bf16 encoders/update + bf16 MXU operands + bf16 volume; the parity
    number then reads the whole bf16 compute-policy deviation against
    the f32-recorded golden — ~0.065 px on CPU, where the kernel/volume
    levers are inactive; the on-chip value bounds the full policy).

    Round 5 (VERDICT r4 #1): also records the *aggregate* EPE-vs-GT per
    arm and its drift against the torch-oracle manifest mean — the
    quantity the north star's 0.02 band actually constrains (per-pixel
    parity drift can exceed it while unbiased rounding leaves the
    aggregate untouched). ``*_hi`` arms re-run with
    ``RAFT_CORR_PRECISION=highest`` (3-pass f32-faithful MXU passes on
    the correlation matmuls) to isolate the MXU default-precision
    contribution and price the fix."""
    import json as _json

    from raft_tpu.evaluate import (ASSETS_DIR, load_predictor,
                                   validate_golden)

    weights = os.path.join(ASSETS_DIR, "golden", "weights.npz")
    with open(os.path.join(ASSETS_DIR, "golden", "manifest.json")) as f:
        manifest = _json.load(f)
    manifest_gt = float(sum(p["epe_vs_gt"] for p in manifest["pairs"])
                        / len(manifest["pairs"]))
    # Same-build CPU aggregates (scripts/golden_cpu_reference.py): the
    # matched-policy anchor — |EPE_tpu - EPE_cpu| at the SAME compute
    # policy is the chip-induced drift the 0.02 band constrains (the
    # bf16 policy's own ~+0.028 aggregate shift exists on CPU too).
    with open(os.path.join(ASSETS_DIR, "golden",
                           "cpu_reference.json")) as f:
        cpu_ref = _json.load(f)
    out = {"manifest_gt_epe": manifest_gt}
    for name, kw, precision in (
            ("all_pairs_f32", {}, None),
            ("alternate_f32", dict(alternate_corr=True), None),
            ("policy_mixed", dict(mixed_precision=True), None),
            ("policy_mixed_alt", dict(alternate_corr=True,
                                      mixed_precision=True), None),
            ("all_pairs_f32_hi", {}, "highest"),
            ("alternate_f32_hi", dict(alternate_corr=True), "highest"),
            ("policy_mixed_hi", dict(mixed_precision=True), "highest"),
            ("policy_mixed_alt_hi", dict(alternate_corr=True,
                                         mixed_precision=True),
             "highest")):

        def run(name=name, kw=kw, precision=precision):
            # corr_impl="fixed": each arm measures ITS engine — the
            # round-4 "auto" eval default would re-dispatch the
            # all-pairs arms onto the on-demand kernel on TPU.
            if precision:
                os.environ["RAFT_CORR_PRECISION"] = precision
            try:
                pred = load_predictor(weights, iters=12,
                                      corr_impl="fixed", **kw)
                res = validate_golden(pred)
            finally:
                os.environ.pop("RAFT_CORR_PRECISION", None)
            # raw float: the f32 arms measure float-noise-scale parity
            # that sub-1e-6 rounding would erase
            out[f"{name}_parity_epe"] = res["golden_parity_epe"]
            out[f"{name}_gt_epe"] = res["golden_gt_epe"]
            out[f"{name}_gt_drift"] = abs(res["golden_gt_epe"]
                                          - manifest_gt)
            policy = ("policy_mixed" if kw.get("mixed_precision")
                      else "all_pairs_f32")
            out[f"{name}_gt_drift_vs_cpu"] = abs(
                res["golden_gt_epe"] - cpu_ref[f"{policy}_gt_epe_cpu"])

        _run_with_band_retry(run, out, name,
                             banded=kw.get("alternate_corr", False))
    return out


def _warped_pairs(key, n, H, W, max_shift=10):
    """Synthetic *learnable* flow data: ``image2`` is ``image1`` rolled by
    a per-sample integer ``(dy, dx)``; ground-truth flow is the constant
    ``(dx, dy)``. Images are low-frequency random patterns (resized up
    8x) so local structure determines the shift — a model that learns
    nothing stays at the ~shift-magnitude EPE plateau, so the loss trend
    must come from actual optimization."""
    k1, k2 = jax.random.split(key)
    low = jax.random.uniform(k1, (n, H // 8, W // 8, 3))
    imgs = jax.image.resize(low, (n, H, W, 3), "linear") * 255.0
    shifts = jax.random.randint(k2, (n, 2), -max_shift, max_shift + 1)

    def roll_one(img, s):
        return jnp.roll(img, (s[0], s[1]), axis=(0, 1))     # (dy, dx)

    img2 = jax.vmap(roll_one)(imgs, shifts)
    flow = jnp.tile(shifts[:, None, None, ::-1].astype(jnp.float32),
                    (1, H, W, 1))                           # (dx, dy)
    return imgs, img2, flow, jnp.ones((n, H, W), jnp.float32)


def train_convergence() -> dict:
    """Sustained on-chip training: loss must *decrease*, not just step
    fast (VERDICT r3 #3). ~500 steps per family at the chairs-stage /
    active-fork configs (reference ``train_mixed.sh:3`` /
    ``train_standard.sh:6``), fixed seed, batches cycling a small pool
    of synthetic warped pairs (overfit-able by construction). Commits
    the every-10-steps loss curve plus steps/sec."""
    from raft_tpu.config import (OursConfig, RAFTConfig, TrainConfig,
                                 sparse_corr_from_env)
    from raft_tpu.models import SparseRAFT
    from raft_tpu.models.raft import RAFT
    from raft_tpu.parallel import create_train_state, make_train_step

    steps = int(os.environ.get("RAFT_CONV_STEPS", "500"))
    # RAFT_CONV_ALT=1 runs the raft family through the on-demand banded
    # engine (the round-4 train default on TPU); the sparse family
    # follows its own config default either way.
    raft_alt = os.environ.get("RAFT_CONV_ALT") == "1"
    every, pool, batch = max(1, steps // 50), 16, 4
    out = {"steps": steps, "batch": batch, "seed": 0,
           "raft_engine": "alternate" if raft_alt else "materialized"}
    for family, make_model, (H, W), tkw in (
            ("raft",
             lambda: RAFT(RAFTConfig(iters=12, mixed_precision=True,
                                     alternate_corr=raft_alt)),
             (368, 496), dict(iters=12)),
            ("sparse",
             lambda: SparseRAFT(OursConfig(
                 mixed_precision=True,
                 alternate_corr=sparse_corr_from_env())),
             (352, 480), dict(model_family="sparse", iters=6,
                              sparse_lambda=0.1))):
        tcfg = TrainConfig(batch_size=batch, image_size=(H, W),
                           num_steps=steps, lr=4e-4, **tkw)
        rng = jax.random.PRNGKey(0)
        i1, i2, fl, va = _warped_pairs(jax.random.PRNGKey(7), pool, H, W)
        state = create_train_state(rng, make_model(), tcfg, (H, W))
        step_fn = make_train_step(tcfg)
        losses = []
        t0 = time.perf_counter()
        for s in range(steps):
            lo = (s * batch) % pool
            sel = (lo + jnp.arange(batch)) % pool
            b = {"image1": i1[sel], "image2": i2[sel],
                 "flow": fl[sel], "valid": va[sel]}
            state, metrics = step_fn(state, b, rng)
            if s % every == 0 or s == steps - 1:
                losses.append(round(float(metrics["loss"]), 4))
        wall = time.perf_counter() - t0
        k = max(1, len(losses) // 10)
        head = sum(losses[:k]) / k
        tail = sum(losses[-k:]) / k
        out[family] = {
            "resolution": [H, W],
            f"loss_curve_every{every}": losses,
            "loss_head_mean": round(head, 4),
            "loss_tail_mean": round(tail, 4),
            "decreased": bool(tail < head),
            "steps_per_sec": round(steps / wall, 3)}
    return out


SECTIONS = {"sparse_train": sparse_train, "raft_train": raft_train,
            "kitti_eval": kitti_eval, "volume_memory": volume_memory,
            "batch1": batch1, "msda_dense": msda_dense,
            "encoder_family": encoder_family,
            "msda_threshold": msda_threshold,
            "golden_on_chip": golden_on_chip,
            "loader_train": loader_train,
            "train_convergence": train_convergence}


def main(argv):
    names = argv or list(SECTIONS)
    print("devices:", jax.devices(), flush=True)
    results = {}
    try:
        with open(OUT_PATH) as f:
            results = json.load(f)
    except Exception:
        pass
    for name in names:
        t0 = time.time()
        try:
            results[name] = SECTIONS[name]()
            results[name]["wall_s"] = round(time.time() - t0, 1)
            print(f"{name}: {json.dumps(results[name])}", flush=True)
        except Exception as e:
            results[name] = {"error": f"{type(e).__name__}: {e}"}
            print(f"{name}: FAILED {e}", flush=True)
        # atomic rewrite: a timeout mid-dump must not leave a truncated
        # artifact where a full committed one stood
        tmp = OUT_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(results, f, indent=1)
        os.replace(tmp, OUT_PATH)
    print("wrote", OUT_PATH)


if __name__ == "__main__":
    main(sys.argv[1:])
