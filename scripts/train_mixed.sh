#!/bin/bash
# Original RAFT 4-stage curriculum (reference train_mixed.sh:3-6):
# chairs -> things -> sintel -> kitti, mixed precision (bf16 on TPU).
mkdir -p checkpoints
python -u train.py --name raft-chairs --stage chairs --validation chairs \
  --lr 0.0004 --num_steps 120000 --batch_size 8 --image_size 368 496 \
  --wdecay 0.0001 --mixed_precision
python -u train.py --name raft-things --stage things --validation sintel \
  --restore_ckpt checkpoints/raft-chairs --lr 0.000125 --num_steps 120000 \
  --batch_size 5 --image_size 400 720 --wdecay 0.0001 --mixed_precision
python -u train.py --name raft-sintel --stage sintel --validation sintel \
  --restore_ckpt checkpoints/raft-things --lr 0.000125 --num_steps 120000 \
  --batch_size 5 --image_size 368 768 --wdecay 0.00001 --gamma 0.85 \
  --mixed_precision
python -u train.py --name raft-kitti --stage kitti --validation kitti \
  --restore_ckpt checkpoints/raft-sintel --lr 0.0001 --num_steps 50000 \
  --batch_size 5 --image_size 288 960 --wdecay 0.00001 --gamma 0.85 \
  --mixed_precision
