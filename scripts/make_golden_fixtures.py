#!/usr/bin/env python
"""Generate the repo-owned golden fixtures under ``assets/``.

Two things the reference ships (or implies) that this repo must own
outright (VERDICT r1 items 3 & 7):

1. ``assets/demo-frames/`` — license-safe *generated* frame pairs filling
   the role of the reference's ``demo-frames/`` Sintel PNGs
   (``/root/reference/README.md:25-28``): procedural band-limited textures
   warped by known affine maps, so each pair also has exact ground-truth
   flow (``.flo``) — frame2(A·p + b) == frame1(p), flow(p) = (A−I)p + b.

2. ``assets/golden/`` — end-to-end golden outputs of the canonical torch
   RAFT (reference ``core/raft.py`` semantics via ``tests/torch_oracle``)
   with deterministic fp16-rounded random weights, stored as:
   ``weights.npz`` (fp16, torch state-dict keys — loadable without torch),
   ``flow_golden_NN.npy`` (f32 final-iteration flow per pair), and
   ``manifest.json`` (iters, seed, per-pair EPE vs GT).  The published
   checkpoints are unreachable here (zero egress —
   ``scripts/download_models.sh`` DNS-fails), so golden parity is pinned
   against this fixed-seed model instead: same converter, same graph as
   the published weights would exercise.

Run from the repo root with the reference mounted (generation only; the
tests that CONSUME these fixtures never touch the reference):

    JAX_PLATFORMS=cpu python scripts/make_golden_fixtures.py
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF_CORE = "/root/reference/core"
H, W = 192, 256
ITERS = 12
SEED = 0

# (name, A (2x2 row-major), b (x, y)) — flow(p) = (A - I) p + b
WARPS = [
    ("translate", np.array([[1.0, 0.0], [0.0, 1.0]]), np.array([3.5, -2.25])),
    ("rotate", None, np.array([-1.5, 2.0])),       # A filled in below (1.2°)
    ("zoom", np.array([[1.03, 0.0], [0.0, 1.03]]), np.array([-2.0, -1.0])),
]
_th = np.deg2rad(1.2)
WARPS[1] = ("rotate",
            np.array([[np.cos(_th), -np.sin(_th)],
                      [np.sin(_th), np.cos(_th)]]), WARPS[1][2])


def make_texture(rng: np.random.Generator, h: int, w: int) -> np.ndarray:
    """Band-limited RGB texture: multi-octave smoothed noise, contrast
    stretched to fill [0, 255]."""
    from scipy.ndimage import gaussian_filter

    tex = np.zeros((h, w, 3), np.float32)
    for sigma, amp in ((12, 1.0), (5, 0.6), (2, 0.35)):
        n = rng.standard_normal((h, w, 3)).astype(np.float32)
        tex += amp * gaussian_filter(n, sigma=(sigma, sigma, 0))
    lo, hi = np.percentile(tex, [1, 99])
    return np.clip((tex - lo) / (hi - lo), 0, 1) * 255.0


def render_pair(rng, A: np.ndarray, b: np.ndarray):
    """frame1(p) = T(p); frame2(q) = T(A^-1 (q - b)); both uint8.

    With q = A p + b, frame2(q) == frame1(p) exactly, so the forward flow
    at p is (A - I) p + b (coordinates are (x, y), origin top-left)."""
    from scipy.ndimage import map_coordinates

    pad = 32   # covers |flow| + interpolation support
    tex = make_texture(rng, H + 2 * pad, W + 2 * pad)

    ys, xs = np.mgrid[0:H, 0:W].astype(np.float64)
    frame1 = tex[pad:pad + H, pad:pad + W]

    Ainv = np.linalg.inv(A)
    # sample T at A^-1 (q - b) for every output pixel q
    qx, qy = xs, ys
    sx = Ainv[0, 0] * (qx - b[0]) + Ainv[0, 1] * (qy - b[1])
    sy = Ainv[1, 0] * (qx - b[0]) + Ainv[1, 1] * (qy - b[1])
    frame2 = np.stack([
        map_coordinates(tex[..., c], [sy + pad, sx + pad], order=3,
                        mode="reflect")
        for c in range(3)], axis=-1)

    flow = np.stack([(A[0, 0] - 1) * xs + A[0, 1] * ys + b[0],
                     A[1, 0] * xs + (A[1, 1] - 1) * ys + b[1]],
                    axis=-1).astype(np.float32)
    return (np.clip(frame1, 0, 255).astype(np.uint8),
            np.clip(frame2, 0, 255).astype(np.uint8), flow)


def main():
    from PIL import Image

    sys.path.insert(0, REPO)
    sys.path.insert(0, os.path.join(REPO, "tests"))
    sys.path.insert(0, REF_CORE)
    from raft_tpu.data.frame_utils import write_flo

    frames_dir = os.path.join(REPO, "assets", "demo-frames")
    golden_dir = os.path.join(REPO, "assets", "golden")
    os.makedirs(frames_dir, exist_ok=True)
    os.makedirs(golden_dir, exist_ok=True)

    rng = np.random.default_rng(7)
    pairs = []
    for i, (name, A, b) in enumerate(WARPS):
        f1, f2, flow = render_pair(rng, A, b)
        p1 = os.path.join(frames_dir, f"frame_{2 * i + 1:04d}.png")
        p2 = os.path.join(frames_dir, f"frame_{2 * i + 2:04d}.png")
        Image.fromarray(f1).save(p1)
        Image.fromarray(f2).save(p2)
        write_flo(os.path.join(golden_dir, f"flow_gt_{i:02d}.flo"), flow)
        pairs.append((name, p1, p2, flow))
        print(f"pair {i} ({name}): |flow| mean "
              f"{np.linalg.norm(flow, axis=-1).mean():.2f}px")

    # --- torch golden outputs with fp16-rounded deterministic weights ---
    import torch
    from torch_oracle import (build_reference_raft_large,
                              build_reference_raft_small,
                              torch_canonical_raft_forward)
    import corr as ref_corr

    manifest = {"iters": ITERS, "seed": SEED, "H": H, "W": W, "pairs": []}
    configs = {
        # (builder, forward kwargs, weights file, flow-file prefix)
        "large": (build_reference_raft_large,
                  dict(radius=4, hdim=128, cdim=128),
                  "weights.npz", "flow_golden"),
        "small": (build_reference_raft_small,
                  dict(radius=3, hdim=96, cdim=64),
                  "weights_small.npz", "flow_golden_small"),
    }
    for size, (builder, fwd_kw, wfile, fprefix) in configs.items():
        fnet, cnet, ub = builder(seed=SEED)

        # fp16 round-trip BEFORE recording goldens, so the stored npz
        # (fp16, half the size) reproduces them bit-for-bit through any
        # loader.
        state = {}
        for prefix, mod in (("fnet", fnet), ("cnet", cnet),
                            ("update_block", ub)):
            sd = mod.state_dict()
            for k, v in sd.items():
                sd[k] = v.half().float()
            mod.load_state_dict(sd)
            for k, v in sd.items():
                state[f"{prefix}.{k}"] = v.numpy().astype(np.float16)
        np.savez_compressed(os.path.join(golden_dir, wfile), **state)

        entries = []
        for i, (name, p1, p2, flow_gt) in enumerate(pairs):
            img1 = np.asarray(Image.open(p1), np.float32)
            img2 = np.asarray(Image.open(p2), np.float32)
            t1 = torch.from_numpy(img1.transpose(2, 0, 1))[None]
            t2 = torch.from_numpy(img2.transpose(2, 0, 1))[None]
            with torch.no_grad():
                flows = torch_canonical_raft_forward(
                    fnet, cnet, ub, t1, t2, iters=ITERS,
                    corr_mod=ref_corr, **fwd_kw)
            final = flows[-1][0].numpy().transpose(1, 2, 0).astype(
                np.float32)
            np.save(os.path.join(golden_dir, f"{fprefix}_{i:02d}.npy"),
                    final)
            epe = float(np.sqrt(((final - flow_gt) ** 2).sum(-1)).mean())
            entries.append({"name": name,
                            "frame1": os.path.basename(p1),
                            "frame2": os.path.basename(p2),
                            "epe_vs_gt": round(epe, 4)})
            print(f"golden {size} {i} ({name}): torch EPE vs GT "
                  f"{epe:.3f}px (random weights — parity anchor, not a "
                  "quality claim)")
        if size == "large":
            manifest["pairs"] = entries        # original layout, kept
        else:
            manifest[size] = {"weights": wfile, "prefix": fprefix,
                              "pairs": entries}

    with open(os.path.join(golden_dir, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=1)
    print("fixtures written to", os.path.join(REPO, "assets"))


if __name__ == "__main__":
    main()
