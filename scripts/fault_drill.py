#!/usr/bin/env python
"""Fault drill: inject each failure class into a tiny training run.

CPU-runnable CI gate for the resilience subsystem
(``raft_tpu/resilience.py``): runs a miniature synthetic-data training
loop with each fault class injected in sequence —

1. transient checkpoint-save I/O errors  -> save retries succeed;
2. corrupt latest checkpoint             -> resume falls back to the
   newest intact step;
3. an unreadable sample                  -> the epoch completes with a
   logged, counted substitution;
4. a NaN batch                           -> the step is skipped, params
   stay finite, the skip is counted;
5. preemption (guard flag)               -> clean checkpoint, resume
   continues from the exact step;
6. async save (``--drill async-save``)   -> dispatch is non-blocking,
   the in-flight step is invisible to restore until the barrier
   commits it, an injected commit failure rolls the step back;
7. multi-host save (``--drill multihost-save``) -> two coordinated
   processes share a checkpoint dir; a targeted injection kills ONE
   host's save commit check and BOTH hosts must roll the step back,
   agree on the older committed step, and restore bit-identical state
   (the torn-step invariant) — per-process loader-state sidecars roll
   back with the step;
8. exact resume (``--drill resume-exact``)  -> training killed
   mid-epoch with one batch pulled but unstepped; resume re-produces
   that batch and the interrupted+resumed run matches an uninterrupted
   control bit-for-bit (batch-index stream, loss trajectory, final
   params), in sync and async checkpoint modes.

Exits nonzero if any recovery path fails (a torn step detected by the
multi-host drill is a failure; any resume divergence likewise). Usage::

    JAX_PLATFORMS=cpu python scripts/fault_drill.py [--drill NAME|--list]
"""

import argparse
import os
import sys
import tempfile
import textwrap
import traceback

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax                                                  # noqa: E402
import jax.numpy as jnp                                     # noqa: E402
import numpy as np                                          # noqa: E402

from raft_tpu import checkpoint as ckpt_lib                 # noqa: E402
from raft_tpu.config import RAFTConfig, TrainConfig         # noqa: E402
from raft_tpu.resilience import (FaultInjector,             # noqa: E402
                                 TrainingDiverged, set_injector)
from raft_tpu.utils.logger import TrainLogger               # noqa: E402

H, W = 64, 96


class SyntheticLoader:
    """Batches with a constant 2px rightward flow."""

    def __init__(self, batch_size=8, n=4, seed=0):
        self.rng = np.random.default_rng(seed)
        self.batch_size = batch_size
        self.n = n

    def __iter__(self):
        for _ in range(self.n):
            img1 = self.rng.uniform(
                0, 255, (self.batch_size, H, W, 3)).astype(np.float32)
            img2 = np.roll(img1, 2, axis=2)
            flow = np.zeros((self.batch_size, H, W, 2), np.float32)
            flow[..., 0] = 2.0
            valid = np.ones((self.batch_size, H, W), np.float32)
            yield {"image1": img1, "image2": img2, "flow": flow,
                   "valid": valid}


def _cfg(num_steps, **kw):
    base = dict(name="drill", num_steps=num_steps, batch_size=8,
                image_size=(H, W), iters=2, val_freq=1000, sum_freq=2)
    base.update(kw)
    return (TrainConfig(**base), RAFTConfig(small=True, iters=2))


def _run(tcfg, mcfg, root, n_batches=8, resume=False):
    from raft_tpu.train import train

    return train(tcfg, mcfg, ckpt_dir=os.path.join(root, "ckpts"),
                 log_dir=os.path.join(root, "logs"),
                 dataloader=SyntheticLoader(n=n_batches), resume=resume,
                 logger=TrainLogger(os.path.join(root, "logs", "drill"),
                                    sum_freq=2, tensorboard=False))


def _finite(state):
    return all(np.all(np.isfinite(np.asarray(l)))
               for l in jax.tree.leaves(state.params))


# -- drills --------------------------------------------------------------


def drill_ckpt_io_errors(root):
    """Transient save failures are retried; the run still completes."""
    set_injector(FaultInjector(ckpt_save_errors=2))
    tcfg, mcfg = _cfg(num_steps=2)
    state = _run(tcfg, mcfg, root)
    d = os.path.join(root, "ckpts", "drill")
    assert int(state.step) == 2, f"run did not complete: {int(state.step)}"
    assert ckpt_lib.latest_step(d) == 2, "final save missing"
    assert _finite(state), "non-finite params"


def drill_corrupt_latest_checkpoint(root):
    """Truncate the newest checkpoint; resume falls back and retrains."""
    tcfg, mcfg = _cfg(num_steps=2)
    _run(tcfg, mcfg, root)                       # saves step 2
    tcfg3, _ = _cfg(num_steps=3)
    _run(tcfg3, mcfg, root, resume=True)         # saves step 3
    d = os.path.join(root, "ckpts", "drill")
    with ckpt_lib.RunCheckpointer(d) as ckptr:
        assert sorted(ckptr.all_steps())[-1] == 3
    step_dir = os.path.join(d, "3")
    for r, _, files in os.walk(step_dir):
        for f in files:                          # preemption mid-save
            open(os.path.join(r, f), "w").close()
    assert ckpt_lib.latest_step(d) == 2, "intact fallback failed"
    tcfg4, _ = _cfg(num_steps=4)
    state = _run(tcfg4, mcfg, root, resume=True)  # resumes from 2
    assert int(state.step) == 4, f"resume-after-corruption: {int(state.step)}"
    assert _finite(state)


def drill_corrupt_sample(root):
    """One unreadable sample: the epoch completes with a substitution."""
    from raft_tpu.data.datasets import DataLoader

    class ArrayDataset:
        def __len__(self):
            return 16

        def __getitem__(self, i):
            img = np.full((H, W, 3), float(i), np.float32)
            return (img, img.copy(),
                    np.zeros((H, W, 2), np.float32),
                    np.ones((H, W), np.float32))

    set_injector(FaultInjector(corrupt_sample_indices=frozenset({5})))
    loader = DataLoader(ArrayDataset(), batch_size=8, shuffle=False,
                        num_workers=2, stall_timeout=0)
    batches = list(loader)
    assert len(batches) == 2, f"epoch truncated: {len(batches)} batches"
    assert loader.stats.substituted_samples == 1, \
        f"substitutions: {loader.stats.substituted_samples}"


def drill_nan_batch(root):
    """One poisoned batch: step skipped, params stay finite, counted."""
    set_injector(FaultInjector(nan_loss_steps=(1,)))
    tcfg, mcfg = _cfg(num_steps=3)
    state = _run(tcfg, mcfg, root)
    assert int(state.step) == 3, f"run did not complete: {int(state.step)}"
    assert _finite(state), "NaN leaked into params"
    import json
    scalars = [json.loads(l) for l in open(os.path.join(
        root, "logs", "drill", "scalars.jsonl"))]
    skipped = max(s.get("skipped_steps", 0) for s in scalars)
    assert skipped == 1, f"skip not counted: {skipped}"


def drill_nan_divergence_abort(root):
    """Every batch poisoned: the loop aborts with a finite checkpoint
    instead of grinding on."""
    set_injector(FaultInjector(nan_loss_steps=tuple(range(64))))
    tcfg, mcfg = _cfg(num_steps=50, max_consecutive_skips=3)
    try:
        _run(tcfg, mcfg, root, n_batches=50)
    except TrainingDiverged:
        pass
    else:
        raise AssertionError("divergence did not abort")
    d = os.path.join(root, "ckpts", "drill")
    assert ckpt_lib.latest_step(d) == 3, "abort checkpoint missing"


def drill_preemption_resume(root):
    """Guard flag mid-run -> exact-step checkpoint -> resume finishes."""
    import raft_tpu.train as train_mod
    from raft_tpu.train import train

    tcfg, mcfg = _cfg(num_steps=50)
    box = [None]

    class SpyGuard(train_mod._PreemptionGuard):
        def __init__(self):
            super().__init__()
            box[0] = self

    class PreemptingLoader(SyntheticLoader):
        def __iter__(self):
            for i, batch in enumerate(super().__iter__()):
                if i == 2:            # SIGTERM lands before batch 3
                    box[0].requested = True
                yield batch

    orig = train_mod._PreemptionGuard
    train_mod._PreemptionGuard = SpyGuard
    try:
        state = train(tcfg, mcfg, ckpt_dir=os.path.join(root, "ckpts"),
                      log_dir=os.path.join(root, "logs"),
                      dataloader=PreemptingLoader(n=50),
                      logger=TrainLogger(os.path.join(root, "logs", "d"),
                                         sum_freq=2, tensorboard=False))
    finally:
        train_mod._PreemptionGuard = orig
    assert int(state.step) == 2, f"preempted at {int(state.step)}, not 2"
    d = os.path.join(root, "ckpts", "drill")
    assert ckpt_lib.latest_step(d) == 2, "preemption checkpoint missing"

    tcfg2, _ = _cfg(num_steps=4)
    state2 = _run(tcfg2, mcfg, root, resume=True)
    assert int(state2.step) == 4, f"resume reached {int(state2.step)}, not 4"
    assert _finite(state2)


class _IndexDataset:
    """Samples carry their own index at ``image1[0, 0, 0]`` — a batch's
    identity is readable from the stacked array, so a drill can compare
    the exact sample stream two runs consumed."""

    def __init__(self, n=32):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        rng = np.random.default_rng(1000 + i)
        img1 = rng.uniform(0, 255, (H, W, 3)).astype(np.float32)
        img1[0, 0, 0] = float(i)                 # identity marker
        img2 = np.roll(img1, 2, axis=1)
        flow = np.zeros((H, W, 2), np.float32)
        flow[..., 0] = 2.0
        valid = np.ones((H, W), np.float32)
        return img1, img2, flow, valid


def _losses(log_dir):
    import json
    path = os.path.join(log_dir, "scalars.jsonl")
    return [rec["loss"] for rec in map(json.loads, open(path))
            if "loss" in rec]


def _params_digest(state):
    import hashlib
    h = hashlib.sha256()
    for leaf in jax.tree.leaves(state.params):
        h.update(np.asarray(jax.device_get(leaf)).tobytes())
    return h.hexdigest()


def drill_resume_exact(root):
    """Kill training mid-epoch, resume, and require a bit-identical
    batch-index stream + loss trajectory + final params versus an
    uninterrupted control run — in sync AND async checkpoint modes.
    The interruption lands with one batch pulled but not yet stepped;
    exact resume must re-produce that batch (not skip it, not replay
    an already-trained one)."""
    import raft_tpu.train as train_mod
    from raft_tpu.data.datasets import DataLoader
    from raft_tpu.train import train

    box = [None]

    class SpyGuard(train_mod._PreemptionGuard):
        def __init__(self):
            super().__init__()
            box[0] = self

    class RecordingLoader(DataLoader):
        """Records the index stream it hands the consumer; optionally
        raises the (spied) preemption flag as the ``preempt_at``-th
        batch is being handed over — the train loop then checkpoints
        WITHOUT stepping it, the pulled-but-unstepped case."""

        def __init__(self, *a, preempt_at=None, **kw):
            super().__init__(*a, **kw)
            self.record = []
            self.preempt_at = preempt_at

        def __iter__(self):
            for b in super().__iter__():
                self.record.append(
                    [int(x) for x in b["image1"][:, 0, 0, 0]])
                if len(self.record) - 1 == self.preempt_at:
                    box[0].requested = True
                yield b

    def make_loader(**kw):
        return RecordingLoader(_IndexDataset(), batch_size=8,
                               shuffle=True, num_workers=2, seed=7,
                               stall_timeout=0, **kw)

    def run(sub, tcfg, mcfg, loader, resume=False):
        return train(tcfg, mcfg,
                     ckpt_dir=os.path.join(sub, "ckpts"),
                     log_dir=os.path.join(sub, "logs"),
                     dataloader=loader, resume=resume,
                     logger=TrainLogger(
                         os.path.join(sub, "logs",
                                      "r" if resume else "f"),
                         sum_freq=1, tensorboard=False))

    for mode in ("sync", "async"):
        tcfg, mcfg = _cfg(num_steps=10, sum_freq=1,
                          async_checkpointing=(mode == "async"))
        ctrl = os.path.join(root, mode, "control")
        kill = os.path.join(root, mode, "kill")

        # Control: 10 uninterrupted steps (2.5 epochs of 4 batches).
        ctrl_loader = make_loader()
        ctrl_state = run(ctrl, tcfg, mcfg, ctrl_loader)
        control = ctrl_loader.record
        assert len(control) == 10, f"[{mode}] control pulled " \
            f"{len(control)} batches, expected 10"

        # Interrupted: preemption flag raised as batch 6 is handed
        # over — 6 steps trained, the 7th batch pulled but unstepped.
        int_loader = make_loader(preempt_at=6)
        orig = train_mod._PreemptionGuard
        train_mod._PreemptionGuard = SpyGuard
        try:
            int_state = run(kill, tcfg, mcfg, int_loader)
        finally:
            train_mod._PreemptionGuard = orig
        assert int(int_state.step) == 6, \
            f"[{mode}] preempted at step {int(int_state.step)}, not 6"
        assert len(int_loader.record) == 7
        assert int_loader.record[:6] == control[:6], \
            f"[{mode}] pre-kill stream diverged from control"

        # The checkpoint carries the exact cursor: epoch 1, 2 batches
        # (16 samples) in — the snapshot at step 6, NOT the pump-ahead
        # position (which already pulled batch 7).
        d = os.path.join(kill, "ckpts", "drill")
        with ckpt_lib.RunCheckpointer(d) as ckptr:
            ls = ckptr.loader_state(6)
        assert ls is not None, f"[{mode}] no loader state in checkpoint"
        assert (ls["epoch"], ls["pos"]) == (1, 16), \
            f"[{mode}] wrong cursor: {ls}"

        # Resume: must re-produce the unstepped batch first, then match
        # the control stream, losses and final params bit-for-bit.
        res_loader = make_loader()
        res_state = run(kill, tcfg, mcfg, res_loader, resume=True)
        assert int(res_state.step) == 10
        assert res_loader.record[0] == int_loader.record[6], \
            f"[{mode}] pulled-but-unstepped batch not replayed"
        assert res_loader.record == control[6:10], \
            (f"[{mode}] DIVERGED: resumed stream "
             f"{res_loader.record} != control {control[6:10]}")

        ctrl_losses = _losses(os.path.join(ctrl, "logs", "f"))
        int_losses = _losses(os.path.join(kill, "logs", "f"))
        res_losses = _losses(os.path.join(kill, "logs", "r"))
        assert len(ctrl_losses) == 10 and len(int_losses) == 6 \
            and len(res_losses) == 4
        assert int_losses + res_losses == ctrl_losses, \
            (f"[{mode}] loss trajectory diverged:\n"
             f"  control  {ctrl_losses}\n"
             f"  stitched {int_losses + res_losses}")
        assert _params_digest(res_state) == _params_digest(ctrl_state), \
            f"[{mode}] final params differ from control"
        print(f"  [{mode}] stream+losses+params bit-identical",
              flush=True)


class _TinyState:
    """Minimal checkpointable state for direct RunCheckpointer drills
    (no training loop needed — save/restore only touch the four array
    fields)."""

    def __init__(self, step):
        self.step = jnp.asarray(step, jnp.int32)
        self.params = {"w": jnp.arange(8, dtype=jnp.float32) * step}
        self.batch_stats = {}
        self.opt_state = {"m": jnp.zeros(8, jnp.float32)}

    def replace(self, **kw):
        import copy
        s = copy.copy(self)
        for k, v in kw.items():
            setattr(s, k, v)
        return s


def drill_async_save(root):
    """Async saves: non-blocking dispatch, commit gating of the
    in-flight step, rollback + resume on an injected commit failure,
    and the train loop's exit barrier."""
    # Integration: the train loop with async checkpointing on completes
    # and its exit barrier commits the final save.
    tcfg, mcfg = _cfg(num_steps=2, async_checkpointing=True)
    state = _run(tcfg, mcfg, root)
    d = os.path.join(root, "ckpts", "drill")
    assert int(state.step) == 2, f"run did not complete: {int(state.step)}"
    assert ckpt_lib.latest_step(d) == 2, "exit barrier did not commit"
    assert _finite(state)

    # Direct: dispatch returns immediately and the in-flight step is
    # invisible until the barrier commits it.
    d2 = os.path.join(root, "direct")
    c = ckpt_lib.RunCheckpointer(d2, async_save=True, save_retries=1,
                                 retry_delay=0.05)
    c.save(_TinyState(1))
    assert c.pending_step == 1, "async save did not stay pending"
    assert c.latest_step() is None, "uncommitted step visible"
    st = c.restore(_TinyState(0))
    assert int(st.step) == 0, "restore observed the in-flight step"
    c.wait_for_pending()
    assert c.latest_step() == 1, "barrier did not commit"

    # Non-blocking proof: dispatch must defer the whole finalize +
    # vote + commit routine to the barrier — the loop keeps stepping
    # (simulated below) while the write runs in background threads.
    finalizes = []
    orig_fin = c._save_with_agreement
    c._save_with_agreement = lambda *a, **kw: (finalizes.append(1),
                                               orig_fin(*a, **kw))[1]
    c.save(_TinyState(2))
    assert not finalizes, "async dispatch ran the finalize inline"
    assert c.pending_step == 2
    work = sum(float(jnp.sum(jnp.ones(64) * i)) for i in range(16))
    assert work > 0                     # steps ran while save in flight
    c.wait_for_pending()
    assert finalizes, "barrier did not finalize"
    c._save_with_agreement = orig_fin
    assert c.latest_step() == 2

    # Injected commit failure past the retry budget: the barrier
    # raises, the torn step is rolled back, resume sees the older one.
    set_injector(FaultInjector(ckpt_commit_errors=8))
    c.save(_TinyState(3))
    try:
        c.wait_for_pending()
    except OSError:
        pass
    else:
        raise AssertionError("commit failure did not surface")
    set_injector(None)
    assert c.latest_step() == 2, \
        f"torn step visible: latest={c.latest_step()}"
    assert not os.path.isdir(os.path.join(d2, "3")), \
        "failed step dir not rolled back"
    st = c.restore(_TinyState(0))
    assert int(st.step) == 2, "resume did not use the committed step"
    c.close()


_MULTIHOST_CHILD = textwrap.dedent("""
    import hashlib, json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = ""          # drop inherited topology flags
    os.environ["COORDINATOR_ADDRESS"] = "localhost:%(port)d"
    # Targeted injection, described the way CI would: host 1's commit
    # health check fails past the retry budget (the mid-save host-death
    # simulation); host 0 stays healthy.
    os.environ["RAFT_FAULT_CKPT_COMMIT_ERRORS"] = "8"
    os.environ["RAFT_FAULT_TARGET_PROCESS"] = "1"
    import jax
    jax.config.update("jax_platforms", "cpu")
    pid = int(sys.argv[1])
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    from raft_tpu.parallel.distributed import init_distributed
    init_distributed(num_processes=2, process_id=pid)

    from raft_tpu import checkpoint as ckpt_lib
    from raft_tpu.resilience import (CheckpointCommitError, FaultInjector,
                                     set_injector)

    root = %(root)r
    mesh = Mesh(np.array(jax.devices()), ("d",))
    rep = NamedSharding(mesh, PartitionSpec())

    def garr(x):
        x = np.asarray(x)
        return jax.make_array_from_callback(x.shape, rep,
                                            lambda idx: x[idx])

    class TinyState:
        def __init__(self, step):
            self.step = garr(np.int32(step))
            self.params = {"w": garr(
                np.arange(8, dtype=np.float32) * step)}
            self.batch_stats = {}
            self.opt_state = {"m": garr(np.zeros(8, np.float32))}
        def replace(self, **kw):
            import copy
            s = copy.copy(self)
            for k, v in kw.items():
                setattr(s, k, v)
            return s

    out = {"pid": pid}
    c = ckpt_lib.RunCheckpointer(root, save_retries=1, retry_delay=0.05)
    set_injector(FaultInjector())         # baseline save is clean
    # Each host checkpoints its own shard cursor alongside the arrays.
    c.save(TinyState(1),
           loader_state={"seed": 0, "epoch": 0, "pos": 8 * (pid + 1)})
    out["baseline_latest"] = c.latest_step()

    # Arm the env-described injection (exercises from_env + targeting).
    set_injector(FaultInjector.from_env())
    torn = False
    try:
        c.save(TinyState(2),
               loader_state={"seed": 0, "epoch": 0, "pos": 999})
    except CheckpointCommitError:
        torn = True
    out["commit_error_raised"] = torn
    set_injector(FaultInjector())

    out["latest_after_tear"] = c.latest_step()
    out["step2_dir_absent"] = not os.path.isdir(
        os.path.join(root, "2"))
    # Torn loader state must roll back WITH the step...
    out["torn_loader_state_absent"] = c.loader_state(2) is None
    st = c.restore(TinyState(0))
    out["restored_step"] = int(jax.device_get(st.step))
    # ...and the committed step must still hold THIS host's cursor.
    ls = c.loader_state(out["restored_step"]) or {}
    out["restored_loader_pos"] = ls.get("pos")
    w = np.asarray(jax.device_get(st.params["w"]))
    out["restored_hash"] = hashlib.sha256(w.tobytes()).hexdigest()

    # Transient one-host blip: one injected failure inside the retry
    # budget — every host retries in lockstep and the step commits.
    set_injector(FaultInjector(ckpt_commit_errors=1, target_process=1))
    c.save(TinyState(3))
    set_injector(FaultInjector())
    out["latest_after_blip"] = c.latest_step()
    c.close()
    print("RESULT " + json.dumps(out), flush=True)
""")


def _free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _scaled_timeout(timeout: int) -> int:
    try:
        load = os.getloadavg()[0] / max(os.cpu_count() or 1, 1)
    except OSError:
        load = 0.0
    return int(timeout * (1.0 + min(3.0, max(0.0, load))))


def drill_multihost_save(root):
    """Two coordinated processes, shared checkpoint dir, one host's
    save killed by targeted injection: both hosts must roll the step
    back, agree on the older committed step and restore bit-identical
    state. A torn step (any host still seeing step 2) fails the drill."""
    import json
    import subprocess

    repo_root = os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "..")
    ckpt_root = os.path.join(root, "shared_ckpts")
    os.makedirs(ckpt_root, exist_ok=True)
    code = _MULTIHOST_CHILD % {"port": _free_port(), "root": ckpt_root}
    env = {**os.environ,
           "PYTHONPATH": os.pathsep.join(
               [os.path.abspath(repo_root),
                os.environ.get("PYTHONPATH", "")]).rstrip(os.pathsep)}
    env.pop("RAFT_FAULT_CKPT_COMMIT_ERRORS", None)
    env.pop("RAFT_FAULT_TARGET_PROCESS", None)
    procs = [subprocess.Popen(
        [sys.executable, "-c", code, str(i)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for i in range(2)]
    results = {}
    timeout = _scaled_timeout(300)
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise AssertionError(
                "multihost drill child timed out (coordinator hang?)")
        assert p.returncode == 0, f"child failed:\n{out[-3000:]}"
        lines = [ln for ln in out.splitlines()
                 if ln.startswith("RESULT ")]
        assert lines, f"no RESULT line:\n{out[-3000:]}"
        r = json.loads(lines[-1][len("RESULT "):])
        results[r["pid"]] = r
    assert set(results) == {0, 1}, f"missing host: {set(results)}"
    for pid, r in results.items():
        assert r["baseline_latest"] == 1, (pid, r)
        assert r["commit_error_raised"], \
            f"host {pid} did not observe the commit failure"
        assert r["latest_after_tear"] == 1, \
            f"TORN STEP: host {pid} sees latest={r['latest_after_tear']}"
        assert r["step2_dir_absent"], \
            f"TORN STEP: failed step dir survived on host {pid}"
        assert r["torn_loader_state_absent"], \
            f"TORN STEP: loader state outlived its step on host {pid}"
        assert r["restored_step"] == 1, (pid, r)
        assert r["restored_loader_pos"] == 8 * (pid + 1), \
            (f"host {pid} restored the wrong shard cursor: "
             f"{r['restored_loader_pos']}")
        assert r["latest_after_blip"] == 3, \
            f"lockstep retry failed on host {pid}: {r}"
    assert results[0]["restored_hash"] == results[1]["restored_hash"], \
        "hosts restored DIFFERENT states from the same committed step"


DRILLS = [
    drill_ckpt_io_errors,
    drill_corrupt_latest_checkpoint,
    drill_corrupt_sample,
    drill_nan_batch,
    drill_nan_divergence_abort,
    drill_preemption_resume,
    drill_resume_exact,
    drill_async_save,
    drill_multihost_save,
]


def _drill_name(fn) -> str:
    return fn.__name__[len("drill_"):].replace("_", "-")


def main(argv=None) -> int:
    by_name = {_drill_name(fn): fn for fn in DRILLS}
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--drill", default="all",
                    choices=["all", *by_name],
                    help="run one drill (default: all)")
    ap.add_argument("--list", action="store_true",
                    help="print available drills and exit")
    args = ap.parse_args(argv)
    if args.list:
        for fn in DRILLS:
            doc = (fn.__doc__ or "").strip().split("\n")[0]
            print(f"{_drill_name(fn):28s} {doc}")
        return 0
    selected = DRILLS if args.drill == "all" else [by_name[args.drill]]

    failures = 0
    for drill in selected:
        name = drill.__name__
        set_injector(None)
        with tempfile.TemporaryDirectory(prefix=f"{name}_") as root:
            print(f"=== {name} ===", flush=True)
            try:
                drill(root)
            except Exception:
                failures += 1
                print(f"FAIL {name}", flush=True)
                traceback.print_exc()
            else:
                print(f"PASS {name}", flush=True)
            finally:
                set_injector(None)
    print(f"\n{len(selected) - failures}/{len(selected)} drills passed",
          flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
