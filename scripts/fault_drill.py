#!/usr/bin/env python
"""Fault drill: inject each failure class into a tiny training run.

CPU-runnable CI gate for the resilience subsystem
(``raft_tpu/resilience.py``): runs a miniature synthetic-data training
loop with each fault class injected in sequence —

1. transient checkpoint-save I/O errors  -> save retries succeed;
2. corrupt latest checkpoint             -> resume falls back to the
   newest intact step;
3. an unreadable sample                  -> the epoch completes with a
   logged, counted substitution;
4. a NaN batch                           -> the step is skipped, params
   stay finite, the skip is counted;
5. preemption (guard flag)               -> clean checkpoint, resume
   continues from the exact step.

Exits nonzero if any recovery path fails. Usage::

    JAX_PLATFORMS=cpu python scripts/fault_drill.py
"""

import os
import sys
import tempfile
import traceback

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax                                                  # noqa: E402
import jax.numpy as jnp                                     # noqa: E402
import numpy as np                                          # noqa: E402

from raft_tpu import checkpoint as ckpt_lib                 # noqa: E402
from raft_tpu.config import RAFTConfig, TrainConfig         # noqa: E402
from raft_tpu.resilience import (FaultInjector,             # noqa: E402
                                 TrainingDiverged, set_injector)
from raft_tpu.utils.logger import TrainLogger               # noqa: E402

H, W = 64, 96


class SyntheticLoader:
    """Batches with a constant 2px rightward flow."""

    def __init__(self, batch_size=8, n=4, seed=0):
        self.rng = np.random.default_rng(seed)
        self.batch_size = batch_size
        self.n = n

    def __iter__(self):
        for _ in range(self.n):
            img1 = self.rng.uniform(
                0, 255, (self.batch_size, H, W, 3)).astype(np.float32)
            img2 = np.roll(img1, 2, axis=2)
            flow = np.zeros((self.batch_size, H, W, 2), np.float32)
            flow[..., 0] = 2.0
            valid = np.ones((self.batch_size, H, W), np.float32)
            yield {"image1": img1, "image2": img2, "flow": flow,
                   "valid": valid}


def _cfg(num_steps, **kw):
    base = dict(name="drill", num_steps=num_steps, batch_size=8,
                image_size=(H, W), iters=2, val_freq=1000, sum_freq=2)
    base.update(kw)
    return (TrainConfig(**base), RAFTConfig(small=True, iters=2))


def _run(tcfg, mcfg, root, n_batches=8, resume=False):
    from raft_tpu.train import train

    return train(tcfg, mcfg, ckpt_dir=os.path.join(root, "ckpts"),
                 log_dir=os.path.join(root, "logs"),
                 dataloader=SyntheticLoader(n=n_batches), resume=resume,
                 logger=TrainLogger(os.path.join(root, "logs", "drill"),
                                    sum_freq=2, tensorboard=False))


def _finite(state):
    return all(np.all(np.isfinite(np.asarray(l)))
               for l in jax.tree.leaves(state.params))


# -- drills --------------------------------------------------------------


def drill_ckpt_io_errors(root):
    """Transient save failures are retried; the run still completes."""
    set_injector(FaultInjector(ckpt_save_errors=2))
    tcfg, mcfg = _cfg(num_steps=2)
    state = _run(tcfg, mcfg, root)
    d = os.path.join(root, "ckpts", "drill")
    assert int(state.step) == 2, f"run did not complete: {int(state.step)}"
    assert ckpt_lib.latest_step(d) == 2, "final save missing"
    assert _finite(state), "non-finite params"


def drill_corrupt_latest_checkpoint(root):
    """Truncate the newest checkpoint; resume falls back and retrains."""
    tcfg, mcfg = _cfg(num_steps=2)
    _run(tcfg, mcfg, root)                       # saves step 2
    tcfg3, _ = _cfg(num_steps=3)
    _run(tcfg3, mcfg, root, resume=True)         # saves step 3
    d = os.path.join(root, "ckpts", "drill")
    with ckpt_lib.RunCheckpointer(d) as ckptr:
        assert sorted(ckptr.all_steps())[-1] == 3
    step_dir = os.path.join(d, "3")
    for r, _, files in os.walk(step_dir):
        for f in files:                          # preemption mid-save
            open(os.path.join(r, f), "w").close()
    assert ckpt_lib.latest_step(d) == 2, "intact fallback failed"
    tcfg4, _ = _cfg(num_steps=4)
    state = _run(tcfg4, mcfg, root, resume=True)  # resumes from 2
    assert int(state.step) == 4, f"resume-after-corruption: {int(state.step)}"
    assert _finite(state)


def drill_corrupt_sample(root):
    """One unreadable sample: the epoch completes with a substitution."""
    from raft_tpu.data.datasets import DataLoader

    class ArrayDataset:
        def __len__(self):
            return 16

        def __getitem__(self, i):
            img = np.full((H, W, 3), float(i), np.float32)
            return (img, img.copy(),
                    np.zeros((H, W, 2), np.float32),
                    np.ones((H, W), np.float32))

    set_injector(FaultInjector(corrupt_sample_indices=frozenset({5})))
    loader = DataLoader(ArrayDataset(), batch_size=8, shuffle=False,
                        num_workers=2, stall_timeout=0)
    batches = list(loader)
    assert len(batches) == 2, f"epoch truncated: {len(batches)} batches"
    assert loader.stats.substituted_samples == 1, \
        f"substitutions: {loader.stats.substituted_samples}"


def drill_nan_batch(root):
    """One poisoned batch: step skipped, params stay finite, counted."""
    set_injector(FaultInjector(nan_loss_steps=(1,)))
    tcfg, mcfg = _cfg(num_steps=3)
    state = _run(tcfg, mcfg, root)
    assert int(state.step) == 3, f"run did not complete: {int(state.step)}"
    assert _finite(state), "NaN leaked into params"
    import json
    scalars = [json.loads(l) for l in open(os.path.join(
        root, "logs", "drill", "scalars.jsonl"))]
    skipped = max(s.get("skipped_steps", 0) for s in scalars)
    assert skipped == 1, f"skip not counted: {skipped}"


def drill_nan_divergence_abort(root):
    """Every batch poisoned: the loop aborts with a finite checkpoint
    instead of grinding on."""
    set_injector(FaultInjector(nan_loss_steps=tuple(range(64))))
    tcfg, mcfg = _cfg(num_steps=50, max_consecutive_skips=3)
    try:
        _run(tcfg, mcfg, root, n_batches=50)
    except TrainingDiverged:
        pass
    else:
        raise AssertionError("divergence did not abort")
    d = os.path.join(root, "ckpts", "drill")
    assert ckpt_lib.latest_step(d) == 3, "abort checkpoint missing"


def drill_preemption_resume(root):
    """Guard flag mid-run -> exact-step checkpoint -> resume finishes."""
    import raft_tpu.train as train_mod
    from raft_tpu.train import train

    tcfg, mcfg = _cfg(num_steps=50)
    box = [None]

    class SpyGuard(train_mod._PreemptionGuard):
        def __init__(self):
            super().__init__()
            box[0] = self

    class PreemptingLoader(SyntheticLoader):
        def __iter__(self):
            for i, batch in enumerate(super().__iter__()):
                if i == 2:            # SIGTERM lands before batch 3
                    box[0].requested = True
                yield batch

    orig = train_mod._PreemptionGuard
    train_mod._PreemptionGuard = SpyGuard
    try:
        state = train(tcfg, mcfg, ckpt_dir=os.path.join(root, "ckpts"),
                      log_dir=os.path.join(root, "logs"),
                      dataloader=PreemptingLoader(n=50),
                      logger=TrainLogger(os.path.join(root, "logs", "d"),
                                         sum_freq=2, tensorboard=False))
    finally:
        train_mod._PreemptionGuard = orig
    assert int(state.step) == 2, f"preempted at {int(state.step)}, not 2"
    d = os.path.join(root, "ckpts", "drill")
    assert ckpt_lib.latest_step(d) == 2, "preemption checkpoint missing"

    tcfg2, _ = _cfg(num_steps=4)
    state2 = _run(tcfg2, mcfg, root, resume=True)
    assert int(state2.step) == 4, f"resume reached {int(state2.step)}, not 4"
    assert _finite(state2)


DRILLS = [
    drill_ckpt_io_errors,
    drill_corrupt_latest_checkpoint,
    drill_corrupt_sample,
    drill_nan_batch,
    drill_nan_divergence_abort,
    drill_preemption_resume,
]


def main() -> int:
    failures = 0
    for drill in DRILLS:
        name = drill.__name__
        set_injector(None)
        with tempfile.TemporaryDirectory(prefix=f"{name}_") as root:
            print(f"=== {name} ===", flush=True)
            try:
                drill(root)
            except Exception:
                failures += 1
                print(f"FAIL {name}", flush=True)
                traceback.print_exc()
            else:
                print(f"PASS {name}", flush=True)
            finally:
                set_injector(None)
    print(f"\n{len(DRILLS) - failures}/{len(DRILLS)} drills passed",
          flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
