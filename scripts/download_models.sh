#!/bin/bash
# Fetch the published pretrained RAFT weights (reference
# download_models.sh:2-3). Convert for this framework with:
#   python -c "from raft_tpu.checkpoint import load_params; load_params('models/raft-things.pth')"
wget https://dl.dropboxusercontent.com/s/4j4z58wuv8o0mfz/models.zip
unzip models.zip
