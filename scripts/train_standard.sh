#!/bin/bash
# The fork's active schedule (reference train_standard.sh:6): chairs stage,
# batch 10, lr 2e-4, 352x480, 1M steps, sparse ("ours") family.
mkdir -p checkpoints
python -u train.py --name raft-ours --stage chairs --model_family sparse \
  --validation chairs --lr 0.0002 --num_steps 1000000 --batch_size 10 \
  --image_size 352 480 --sparse_lambda 0.1
