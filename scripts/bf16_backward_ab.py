#!/usr/bin/env python
"""bf16-MXU-operand training A/B (VERDICT r3 weak #6 / next #8).

``corr_mxu_dtype="bfloat16"`` quadruples the on-demand kernel's MXU
throughput but rounds both the forward correlation operands and the
backward's assembled cotangent to bfloat16 (corr_pallas.py backward).
That is fine for the inference headline; the open question was whether
the *gradient* rounding measurably changes training. This runs the same
fixed-seed miniature training twice through the Pallas kernel (interpret
mode off-TPU — bit-faithful emulation of the bf16 casts), f32 vs bf16
operands, and records the loss-trajectory delta.

Decision input for whether ``corr_mxu_dtype="auto"`` may ever resolve to
bf16 for training (today it deliberately does not — config.py gates the
auto lever to inference, mirroring the reference's pre-corr f32 casts at
``core/raft.py:103-104``).

CPU-cheap by design: run anywhere, writes BF16_BACKWARD_AB.json.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# Route the model's alternate-corr lookups through the Pallas kernel even
# off-TPU (interpret mode) — the jnp fallback ignores mxu_dtype entirely.
os.environ["RAFT_CORR_BACKEND"] = "pallas"

import jax
import jax.numpy as jnp

STEPS = int(os.environ.get("RAFT_AB_STEPS", "20"))
H, W, BATCH, POOL = 64, 96, 2, 4
OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "BF16_BACKWARD_AB.json")


def _data(key):
    from tpu_extras_bench import _warped_pairs
    return _warped_pairs(key, POOL, H, W, max_shift=4)


def run_arm(mxu_dtype: str) -> list:
    from raft_tpu.config import RAFTConfig, TrainConfig
    from raft_tpu.models.raft import RAFT
    from raft_tpu.parallel import create_train_state, make_train_step

    tcfg = TrainConfig(batch_size=BATCH, image_size=(H, W),
                       num_steps=STEPS, lr=2e-4, iters=6)
    model = RAFT(RAFTConfig(small=True, iters=6, alternate_corr=True,
                            corr_mxu_dtype=mxu_dtype))
    rng = jax.random.PRNGKey(0)
    i1, i2, fl, va = _data(jax.random.PRNGKey(7))
    state = create_train_state(rng, model, tcfg, (H, W))
    step_fn = make_train_step(tcfg, donate=False)
    losses = []
    for s in range(STEPS):
        lo = (s * BATCH) % POOL
        sel = (lo + jnp.arange(BATCH)) % POOL
        b = {"image1": i1[sel], "image2": i2[sel],
             "flow": fl[sel], "valid": va[sel]}
        state, metrics = step_fn(state, b, rng)
        losses.append(float(metrics["loss"]))
    return losses


def main():
    t0 = time.time()
    f32 = run_arm("float32")
    bf16 = run_arm("bfloat16")
    deltas = [abs(a - b) / max(abs(a), 1e-9) for a, b in zip(f32, bf16)]
    payload = {
        "steps": STEPS, "batch": BATCH, "resolution": [H, W],
        "backend": jax.default_backend(),
        "loss_f32": [round(x, 5) for x in f32],
        "loss_bf16": [round(x, 5) for x in bf16],
        "rel_delta_max": round(max(deltas), 5),
        "rel_delta_final": round(deltas[-1], 5),
        "f32_decreased": f32[-1] < f32[0],
        "bf16_decreased": bf16[-1] < bf16[0],
        "wall_s": round(time.time() - t0, 1),
    }
    with open(OUT, "w") as f:
        json.dump(payload, f, indent=1)
    print(json.dumps(payload))


if __name__ == "__main__":
    main()
