#!/bin/bash
# Poll the accelerator tunnel; when it answers, run the benchmark suite
# once and leave the artifacts in the repo root. Safe to leave running —
# it exits after one capture with a numeric headline value (an "error"
# from a secondary metric doesn't invalidate preserved headline numbers;
# a capture with "value": null retries) or after MAX_TRIES probes.
cd "$(dirname "$0")/.."
MAX_TRIES=${MAX_TRIES:-60}
SLEEP_S=${SLEEP_S:-600}
for i in $(seq 1 "$MAX_TRIES"); do
  # 420s probe: SIGTERM mid-backend-init can wedge the tunnel, and slow
  # recoveries legitimately take >5 min to answer
  if timeout 420 python -c "import jax; assert jax.devices()[0].platform != 'cpu'" 2>/dev/null; then
    echo "tunnel up on probe $i ($(date -u +%H:%M:%SZ)); capturing" | tee -a tunnel_watch.log
    RAFT_BENCH_TOTAL_DEADLINE_S=1500 \
      timeout 1800 python bench.py > BENCH_CAPTURE.json 2> bench_capture.log
    # a numeric headline value is success even if a secondary metric
    # attached an "error" (bench preserves completed headline numbers);
    # must check the TOP-LEVEL value only — failure artifacts embed a
    # nested non-null value inside last_local_capture
    # (parse the LAST line only — third-party libraries may print to
    # stdout before bench.py's single JSON artifact line)
    if ! python -c "
import json, sys
lines = [l for l in open('BENCH_CAPTURE.json') if l.strip()]
sys.exit(0 if lines and json.loads(lines[-1]).get('value') is not None
         else 1)"; then
      echo "probe $i: bench capture failed (tunnel flap?); retrying" | tee -a tunnel_watch.log
      sleep "$SLEEP_S"
      continue
    fi
    # committed-name copy: bench.py embeds the newest local capture as
    # last_local_capture context in any later null-value driver artifact
    cp BENCH_CAPTURE.json BENCH_local.json
    if ! timeout 3600 python scripts/tpu_extras_bench.py >> tunnel_watch.log 2>&1; then
      echo "probe $i: extras sweep failed; bench capture kept" | tee -a tunnel_watch.log
    fi
    echo "capture done ($(date -u +%H:%M:%SZ))" | tee -a tunnel_watch.log
    # Guarded auto-commit: the capture validated non-null above, and the
    # round may end before an interactive session can commit it. Only
    # the JSON capture artifacts are committed — .jax_cache stays local
    # (gitignored): the driver reuses the on-disk cache in this same
    # repo dir, and machine-specific binary XLA blobs don't belong in
    # history. TPU_EXTRAS.json is only staged if it still parses (the
    # 3600s timeout can kill the sweep mid-rewrite), and the commit is
    # pathspec-scoped so nothing a concurrent session staged gets swept
    # in.
    PATHS="BENCH_local.json"
    if python -c "import json; json.load(open('TPU_EXTRAS.json'))" 2>> tunnel_watch.log; then
      PATHS="$PATHS TPU_EXTRAS.json"
    else
      echo "TPU_EXTRAS.json invalid; not committing it" | tee -a tunnel_watch.log
    fi
    for p in $PATHS; do git add "$p" 2>> tunnel_watch.log; done
    git commit -m "TPU capture: headline bench + extras sweep (tunnel recovery)" \
      -- $PATHS >> tunnel_watch.log 2>&1 \
      || echo "auto-commit failed (see log)" | tee -a tunnel_watch.log
    exit 0
  fi
  echo "probe $i: tunnel down ($(date -u +%H:%M:%SZ))" >> tunnel_watch.log
  sleep "$SLEEP_S"
done
echo "gave up after $MAX_TRIES probes" | tee -a tunnel_watch.log
exit 1
