#!/bin/bash
# Poll the accelerator tunnel; when it answers, run the benchmark suite
# once and leave the artifacts in the repo root. Safe to leave running —
# it exits after one capture with a numeric headline value (an "error"
# from a secondary metric doesn't invalidate preserved headline numbers;
# a capture with "value": null retries) or after MAX_TRIES probes.
cd "$(dirname "$0")/.."
MAX_TRIES=${MAX_TRIES:-60}
SLEEP_S=${SLEEP_S:-600}
for i in $(seq 1 "$MAX_TRIES"); do
  # 420s probe: SIGTERM mid-backend-init can wedge the tunnel, and slow
  # recoveries legitimately take >5 min to answer
  if timeout 420 python -c "import jax; assert jax.devices()[0].platform != 'cpu'" 2>/dev/null; then
    echo "tunnel up on probe $i ($(date -u +%H:%M:%SZ)); capturing" | tee -a tunnel_watch.log
    RAFT_BENCH_DEADLINE_S=600 RAFT_BENCH_TOTAL_DEADLINE_S=1500 \
      timeout 1800 python bench.py > BENCH_CAPTURE.json 2> bench_capture.log
    # a numeric headline value is success even if a secondary metric
    # attached an "error" (bench preserves completed headline numbers)
    if ! grep -q '"value": [0-9]' BENCH_CAPTURE.json; then
      echo "probe $i: bench capture failed (tunnel flap?); retrying" | tee -a tunnel_watch.log
      sleep "$SLEEP_S"
      continue
    fi
    if ! timeout 3600 python scripts/tpu_extras_bench.py >> tunnel_watch.log 2>&1; then
      echo "probe $i: extras sweep failed; bench capture kept" | tee -a tunnel_watch.log
    fi
    echo "capture done ($(date -u +%H:%M:%SZ))" | tee -a tunnel_watch.log
    exit 0
  fi
  echo "probe $i: tunnel down ($(date -u +%H:%M:%SZ))" >> tunnel_watch.log
  sleep "$SLEEP_S"
done
echo "gave up after $MAX_TRIES probes" | tee -a tunnel_watch.log
exit 1
