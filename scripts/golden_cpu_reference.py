"""Regenerate ``assets/golden/cpu_reference.json`` — the same-build CPU
golden aggregates that anchor the on-chip EPE-drift bound (VERDICT r4
#1). Run on any host with ``JAX_PLATFORMS=cpu`` (forced below); commit
the refreshed file whenever the golden fixtures or the model's numerics
change.

The decomposition this enables (recorded by ``tpu_extras_bench.py
golden_on_chip`` as ``*_gt_drift_vs_cpu``):

    |EPE_gt_tpu - EPE_gt_cpu|  at MATCHED compute policy

is the chip-induced aggregate drift the north star's 0.02 band
constrains. The bf16 mixed-precision policy's own aggregate shift
(~+0.028 vs the f32 oracle, measured on CPU where no TPU arithmetic is
involved) is a *policy* property a user opts into — the reference's AMP
training makes the same trade (reference ``train.py:21-24``).
"""

from __future__ import annotations

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    from raft_tpu.evaluate import ASSETS_DIR, load_predictor, validate_golden

    weights = os.path.join(ASSETS_DIR, "golden", "weights.npz")
    out = {
        "_comment": (
            "Same-build CPU golden aggregates (scripts/"
            "golden_cpu_reference.py). Anchor for the on-chip "
            "|EPE_gt_tpu - EPE_gt_cpu| drift bound (VERDICT r4 #1): the "
            "0.02 band constrains chip-vs-baseline at MATCHED compute "
            "policy; the bf16 policy's own aggregate shift (~0.028, "
            "present on CPU where no TPU arithmetic is involved) is a "
            "policy property, not chip drift.")}
    for name, kw in (("all_pairs_f32", {}),
                     ("policy_mixed", dict(mixed_precision=True))):
        pred = load_predictor(weights, iters=12, corr_impl="fixed", **kw)
        res = validate_golden(pred)
        out[f"{name}_gt_epe_cpu"] = res["golden_gt_epe"]
        out[f"{name}_parity_epe_cpu"] = res["golden_parity_epe"]
    path = os.path.join(ASSETS_DIR, "golden", "cpu_reference.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print("wrote", path, json.dumps({k: v for k, v in out.items()
                                     if not k.startswith("_")}))


if __name__ == "__main__":
    main()
