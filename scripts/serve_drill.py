#!/usr/bin/env python
"""CPU drills for the serving engine (CI gates, run in minutes).

1. smoke (``--drill smoke``) — warms two buckets, fires 50 concurrent
   requests, exits nonzero on ANY dropped or bit-incorrect response or
   any post-warmup XLA compile (the original serving gate).
2. breaker-isolation (``--drill breaker-isolation``) — a poisoned
   request (``RAFT_FAULT_SERVING_POISON_NTH``) fails ALONE while its
   batch neighbors serve bit-exact via the retry-as-singles isolation
   pass; then injected dispatch errors
   (``RAFT_FAULT_SERVING_DISPATCH_ERRORS``) trip the circuit breaker
   OPEN (submit fails fast with ``EngineUnhealthy``), a failed half-open
   probe re-opens it, and a healthy probe closes it again.
3. reload-under-load (``--drill reload-under-load``) — the headline
   drill: 50 concurrent clients stream requests while a background
   trainer commits two checkpoints — one good (passes the canary, hot
   swap) and one fault-injected bad (NaN params, canary rollback). The
   gate: zero dropped and zero bit-incorrect responses across the swap
   (every response bit-matches exactly the old OR the new model — never
   a blend, never garbage), exactly one swap, exactly one rollback,
   zero fresh XLA compiles after warmup (the standby serves through the
   shared bucket executables), and the breaker provably opens and
   recovers under injected dispatch errors on the same engine.
4. fleet (``--drill fleet``) — 3-replica fleet: kill a replica under
   50 concurrent clients (zero dropped/bit-incorrect, router
   re-balances), rolling reload (one canary, zero wave compiles), NaN
   checkpoint rolls the whole fleet back.
5. streaming (``--drill streaming``) — N sticky streaming sessions
   against a 3-replica fleet; kill the most-pinned replica mid-run:
   affected streams drop state and cold-restart elsewhere with zero
   dropped responses and zero fresh compiles, their stats honestly
   showing the restart's extra encoder MISS.
6. brownout (``--drill brownout``) — burst LOW traffic past capacity
   against a quality-ladder engine: the brownout controller steps LOW
   down the pre-warmed iters ladder (every degraded response
   bit-matches exactly one level), 0 HIGH responses degraded, 0
   dropped before ladder exhaustion, recovery to full quality with
   hysteresis, and 0 fresh XLA compiles across the episode.
7. pallas-kernels (``--drill pallas-kernels``) — the fused-kernel warm
   path: a NON-small banded-correlation engine with the whole Pallas
   chain forced (``RAFT_CORR_BACKEND=pallas`` + ``RAFT_STEP_PALLAS=1``
   + ``RAFT_MOTION_PALLAS=1`` + ``RAFT_GRU_PALLAS=1``, all trace-time
   flags baked into the bucket executables) warms up, serves a
   concurrent load bit-exactly, and triggers ZERO post-warmup XLA
   compiles — proving the round-5/6/7/10 kernels ride the serving
   zero-compile contract.
8. highres (``--drill highres``) — the spatially-sharded serving path
   (forces ``--xla_force_host_platform_device_count=8`` before jax
   initializes). Part A: one engine serves mixed highres+batch-1
   traffic with the sharded bucket on its own dispatch stream — all
   bit-exact, zero post-warmup compiles. Part B: a heterogeneous
   3-replica fleet (two mesh-capable, one not) is killed under load —
   sharded requests fail over to the surviving mesh replica with zero
   drops; with both mesh replicas dead they shed CLEANLY with an error
   naming the mesh (never wedging a stream) while the mesh-less
   replica keeps serving small traffic.
9. wire (``--drill wire``) — the uint8 wire format under fire: proves
   up front that uint8 and integral-float32 references are
   bit-identical, then kills a replica of a 3-replica fleet under 50
   concurrent clients submitting MIXED-dtype traffic (uint8, integral
   float32, non-integral float32 — the first two share the u8 wire,
   the last rides f32). Gate: zero dropped, zero bit-incorrect, zero
   post-warmup compiles (dual-dtype warmup covers both wires on every
   replica, spares included), plus a ``low_res=True`` response that
   bit-matches the reference 1/8-grid flow and host-upsamples back to
   the full frame shape.

10. trace (``--drill trace``) — request-scoped tracing under the full
    traffic mix: a brownout-ladder engine serves batched HIGH traffic
    plus a LOW burst, then a 3-replica fleet takes batched load with a
    mid-load replica kill (one injected failover) and streaming
    sessions — all with tracing ON. Writes ``/tmp/raft_trace.json``
    and gates on: well-formed Chrome trace-event JSON, every opened
    request root span closed (``open_flows() == []``), failover hops
    visible as ``failover_hop`` instants on the request track, and
    ZERO post-warmup XLA compiles with tracing enabled (tracing must
    not perturb the executable cache).

11. gateway (``--drill gateway``) — the multi-process kill-a-process
    proof: 3 replica worker PROCESSES (own heaps, own XLA clients)
    publish heartbeat leases; the gateway routes 50-client load over
    live lease-holders; one worker is SIGKILLed mid-load. Gate: 0
    dropped, 0 bit-incorrect (post-acceptance failures retry on the
    next live owner; the dead worker's lease drops immediately), the
    supervisor respawns the victim with backoff, and the respawn
    rejoins routing only after its warmup completes and its lease
    reports the fleet's checkpoint step — with 0 post-warmup compiles
    reported by every worker's lease, and per-worker liveness/respawn/
    retry gauges live in the registry's Prometheus export.

12. autoscale (``--drill autoscale``) — self-healing capacity: burst
    load against a 1-worker fleet drives the metrics-fed autoscaler to
    spawn a second worker process (unroutable until its lease proves
    warmup; the incumbent's brownout controller provably covers the
    gap), a partition-injected worker
    (``RAFT_FAULT_WORKER_PARTITION_S``) loses its request to the
    gateway's hop-stall failover rather than a client timeout, and
    when load drops the autoscaler drains the least-loaded worker
    gracefully — in-flight work finishes, the lease is removed, the
    worker exits 0 and the supervisor retires the slot without
    counting a crash or respawning. Gate: 0 dropped, 0 bit-incorrect,
    ≥1 failover retry, and 0 post-warmup compiles on every survivor,
    with the autoscaler's decision gauges live in the registry export.

13. edge (``--drill edge``) — the hardened HTTP front door: concurrent
    HTTP/1.1 clients drive edge → gateway → 3 worker processes (one
    bound ``0.0.0.0`` with an advertised non-loopback address, pinged
    routable by the gateway's own transport) through a mid-load worker
    SIGKILL, an injected slowloris (``RAFT_FAULT_EDGE_SLOWLORIS_S`` —
    the edge's header-read deadline reaps the trickling connection and
    the absorbed client retries clean) and an injected client abort
    (``RAFT_FAULT_EDGE_CLIENT_ABORT_NTH`` — no poison downstream).
    Gate: 0 dropped, 0 bit-incorrect, 0 post-warmup compiles, edge
    counters live in the Prometheus export, and a SIGTERM drains
    edge → gateway → workers IN ORDER with ``/readyz`` answering 503
    while the listener is still open (the load-balancer grace window).

14. reliability (``--drill reliability``) — end-to-end request
    reliability. Stage A: a single-owner fleet under injected reply
    loss (``RAFT_FAULT_WORKER_SOCKET_DROP`` — the reply is computed,
    cached, then the socket is RST) and duplicate delivery
    (``RAFT_FAULT_WORKER_DUP_DELIVERY_NTH``): every lost reply is
    served by the gateway's same-key chain rewalk from the worker's
    idempotency cache, bit-exact, and the lease-published audit
    counters prove the EXACTLY-ONCE EFFECT — worker computes equals
    unique requests despite more deliveries than requests. Stage B: a
    3-worker fleet takes a mid-load SIGKILL (post-acceptance retries,
    0 dropped), then a partition-injected worker stalls its primary
    bucket and the gateway's tail-latency hedge rescues the request
    (hedge fires, hedge wins, budget-capped), and an SDC-injected
    worker (``RAFT_FAULT_WORKER_SDC_NTH``) fails its sentinel
    self-check, goes QUARANTINED (non-routable), is recycled by the
    supervisor WITHOUT crash accounting, and its replacement rejoins
    routable. Gate: 0 dropped, 0 bit-incorrect, 0 post-warmup
    compiles everywhere.

Correctness is bit-exact: on this script's single-process default
topology the batch-1 ``__call__`` path and the batched serve path are
bit-identical; under a forced multi-device topology
(``XLA_FLAGS=--xla_force_host_platform_device_count=N``) the checks
automatically use the same-executable batched reference instead, exact
on any topology (see loadgen docstring).

Usage::

    JAX_PLATFORMS=cpu python scripts/serve_drill.py [--drill NAME|--list]
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import threading
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

N_REQUESTS = 50
CONCURRENCY = 8
# Two raw shapes per bucket: (36,60) and (33,57) share the (40,64)
# bucket; (52,76) pads to (56,80) — two buckets total, three raw shapes.
SHAPES = [(36, 60), (33, 57), (52, 76)]
BUCKETS = ((36, 60), (52, 76))


def _make_predictor():
    from raft_tpu.evaluate import load_predictor
    return load_predictor("random", small=True, iters=2)


def _references(predictor, frames, max_batch: int):
    """(references, description): bit-exact ground truth for this
    topology — direct batch-1 on a single device, same-executable
    batched elsewhere."""
    import jax
    from raft_tpu.serving import loadgen

    if jax.device_count() == 1:
        return (loadgen.reference_flows(predictor, frames),
                "direct __call__ (batch-1, bit-exact single-device)")
    return (loadgen.batched_reference_flows(predictor, frames,
                                            max_batch=max_batch),
            f"same-executable batched ({jax.device_count()} devices: "
            "cross-executable float order differs)")


def drill_smoke(root):
    """50 concurrent requests: all served, all bit-exact, zero
    post-warmup compiles."""
    from raft_tpu.serving import (CompileWatch, ServingConfig,
                                  ServingEngine, loadgen)

    predictor = _make_predictor()
    frames = loadgen.make_frames(SHAPES, per_shape=2, seed=11)
    refs, ref_kind = _references(predictor, frames, max_batch=4)

    engine = ServingEngine(predictor, ServingConfig(
        max_batch=4, max_wait_ms=3.0, buckets=BUCKETS))
    warm = engine.warmup()
    engine.start(warmup=False)
    try:
        with CompileWatch() as watch:
            res = loadgen.run_load(engine, frames, n_requests=N_REQUESTS,
                                   concurrency=CONCURRENCY,
                                   references=refs)
    finally:
        engine.close()

    print(f"  {res['completed']}/{N_REQUESTS} responses, "
          f"{res['throughput_rps']:.1f} req/s at concurrency "
          f"{CONCURRENCY}; reference = {ref_kind}")
    warm_desc = ", ".join(f"{k}: {int(v['compiles'])}"
                          for k, v in warm.items())
    print(f"  warmup: {{bucket: compiles}} = {{{warm_desc}}}")
    print("  metrics:", engine.metrics.report())
    print("  host stages:", engine.stages.report())
    assert res["completed"] == N_REQUESTS, \
        f"completed {res['completed']}/{N_REQUESTS}"
    assert not res["dropped"], f"dropped requests: {res['dropped']}"
    assert not res["mismatched"], \
        f"incorrect responses: {res['mismatched']}"
    assert len(warm) == len(BUCKETS), \
        f"warmup covered {len(warm)} of {len(BUCKETS)} buckets"
    assert not watch.compiles, \
        f"{watch.compiles} fresh XLA compile(s) after warmup"


def _await_metric(read, target, timeout: float, what: str):
    deadline = time.monotonic() + timeout
    while read() < target:
        if time.monotonic() >= deadline:
            raise AssertionError(
                f"timed out waiting for {what} >= {target} "
                f"(at {read()})")
        time.sleep(0.01)


def _prove_breaker(engine, im1, im2, expected):
    """Shared breaker proof (run on a live engine): 2 injected dispatch
    errors trip the threshold-2 breaker OPEN, submit fails fast, a
    failed half-open probe re-trips, a healthy probe closes it and
    serves ``expected`` bit-exact."""
    import numpy as np

    from raft_tpu.resilience import FaultInjector, set_injector
    from raft_tpu.serving import CircuitBreaker, EngineUnhealthy

    trips_before = engine.breaker.trips
    cooldown = engine.config.breaker_cooldown_s
    set_injector(FaultInjector(serving_dispatch_errors=3))
    try:
        # Failures 1+2: consecutive injected dispatch errors -> OPEN.
        for i in range(2):
            try:
                engine.submit(im1, im2).result(60)
            except RuntimeError as e:
                assert "injected serving dispatch" in str(e), e
            else:
                raise AssertionError("injected dispatch error not "
                                     "surfaced to the client")
        assert engine.breaker.state == CircuitBreaker.OPEN, \
            f"breaker not OPEN after 2 failures: {engine.breaker.state}"
        assert engine.health()["state"] == "open"
        # OPEN: submit fails fast without touching the queue.
        try:
            engine.submit(im1, im2)
        except EngineUnhealthy:
            pass
        else:
            raise AssertionError("submit admitted while breaker OPEN")
        # Half-open probe burns the 3rd injected error -> re-trips.
        time.sleep(cooldown + 0.05)
        assert engine.breaker.state == CircuitBreaker.HALF_OPEN
        try:
            engine.submit(im1, im2).result(60)
        except RuntimeError as e:
            assert "injected serving dispatch" in str(e), e
        else:
            raise AssertionError("failed probe did not fail the client")
        assert engine.breaker.state == CircuitBreaker.OPEN, \
            "failed half-open probe did not re-open the breaker"
        # Healthy probe (injector exhausted) closes it.
        time.sleep(cooldown + 0.05)
        flow = engine.submit(im1, im2).result(60)
        assert engine.breaker.state == CircuitBreaker.CLOSED, \
            "healthy probe did not close the breaker"
        assert np.array_equal(flow, expected), \
            "post-recovery response not bit-exact"
    finally:
        set_injector(None)
    assert engine.breaker.trips == trips_before + 2, \
        f"expected 2 new trips, got {engine.breaker.trips - trips_before}"
    print(f"  breaker: opened, fast-failed, re-opened on failed probe, "
          f"closed on healthy probe (trips {engine.breaker.trips})")


def drill_breaker_isolation(root):
    """A poisoned request fails alone (neighbors served bit-exact via
    isolation singles); injected dispatch errors open -> half-open ->
    close the circuit breaker."""
    import numpy as np

    from raft_tpu.resilience import FaultInjector, set_injector
    from raft_tpu.serving import ServingConfig, ServingEngine, loadgen

    predictor = _make_predictor()
    frames = loadgen.make_frames([(36, 60)], per_shape=3, seed=23)
    refs, _ = _references(predictor, frames, max_batch=4)

    engine = ServingEngine(predictor, ServingConfig(
        max_batch=4, max_wait_ms=40.0, buckets=((36, 60),),
        breaker_threshold=2, breaker_cooldown_s=0.3))
    engine.start()
    try:
        # Poison every 3rd submit: requests 1..3 batch together (the
        # 40ms deadline lets all three queue), the batch dispatch sees
        # the poison and fails, isolation retries each as a single —
        # 1 and 2 serve bit-exact, 3 fails alone.
        set_injector(FaultInjector(serving_poison_nth=3))
        futs = [engine.submit(*frames[i]) for i in range(3)]
        set_injector(None)
        for i in (0, 1):
            assert np.array_equal(futs[i].result(60), refs[i]), \
                f"isolated neighbor {i} not bit-exact"
        try:
            futs[2].result(60)
        except RuntimeError as e:
            assert "poisoned" in str(e), e
        else:
            raise AssertionError("poisoned request did not fail")
        assert engine.metrics.isolated_retries == 2, \
            f"isolated_retries={engine.metrics.isolated_retries}, want 2"
        assert engine.metrics.errors >= 1
        print("  isolation: poisoned request failed alone, 2 neighbors "
              "served bit-exact on the singles pass")

        # One clean request resets the failure streak (the poisoned
        # single failed last, leaving it at 1) so the threshold-2
        # breaker proof below starts from a clean slate.
        assert np.array_equal(engine.submit(*frames[0]).result(60),
                              refs[0])
        _prove_breaker(engine, *frames[0], expected=refs[0])
        print("  metrics:", engine.metrics.report())
    finally:
        set_injector(None)
        engine.close()


def drill_reload_under_load(root):
    """Hot reload under 50 concurrent clients: good checkpoint swaps
    (canary pass), bad checkpoint rolls back (canary fail), zero
    dropped/incorrect responses, zero post-warmup compiles, breaker
    opens and recovers on the same engine."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from raft_tpu.checkpoint import RunCheckpointer
    from raft_tpu.serving import (CompileWatch, HotReloader,
                                  ReloadConfig, ServingConfig,
                                  ServingEngine, loadgen)

    predictor = _make_predictor()
    frames = loadgen.make_frames(SHAPES, per_shape=2, seed=31)
    refs_old, ref_kind = _references(predictor, frames, max_batch=4)

    # The two checkpoints the background "trainer" will commit: step 1
    # nudges every param by 0.1% (a plausible consecutive-training
    # delta — must pass the canary), step 2 is NaN-filled (a diverged
    # run's export — must fail the finite check and roll back).
    vars_cur = predictor.variables
    params_good = jax.tree_util.tree_map(
        lambda x: x * (1 + 1e-3), vars_cur["params"])
    params_bad = jax.tree_util.tree_map(
        lambda x: jnp.full_like(x, jnp.nan), vars_cur["params"])
    vars_good = dict(vars_cur, params=params_good)
    refs_new, _ = _references(predictor.clone_with_variables(vars_good),
                              frames, max_batch=4)

    class _ServeState:
        """Checkpointable trainer state carrying the predictor's real
        param tree (what load_params will hand the reloader)."""

        def __init__(self, step, params):
            self.step = jnp.asarray(step, jnp.int32)
            self.params = params
            self.batch_stats = vars_cur.get("batch_stats", {})
            self.opt_state = {"m": jnp.zeros(4, jnp.float32)}

    ckpt_dir = os.path.join(root, "ckpts")
    # Warm orbax's one-time internal jit (first save in a process
    # compiles once) against a scratch dir, so the zero-compile watch
    # below measures only the serving path. A production trainer is a
    # separate process; this drill shares one.
    scratch = RunCheckpointer(os.path.join(root, "scratch"))
    scratch.save(_ServeState(1, params_good))
    scratch.close()
    trainer = RunCheckpointer(ckpt_dir)

    engine = ServingEngine(predictor, ServingConfig(
        max_batch=4, max_wait_ms=3.0, buckets=BUCKETS,
        breaker_threshold=2, breaker_cooldown_s=0.3))
    warm = engine.warmup()
    assert len(warm) == len(BUCKETS)
    engine.start(warmup=False)
    reloader = HotReloader(
        engine, ckpt_dir, canary_frames=[frames[0]],
        config=ReloadConfig(canary_max_epe=50.0))
    # Two waves keep the stream saturated across the swap without
    # racing it: wave 1 (old-or-new acceptance) is in flight while the
    # good checkpoint lands — on a slow box it may even drain entirely
    # during the canary — and wave 2 starts only after the swap is
    # confirmed, so every one of its responses must bit-match the NEW
    # model exactly.
    n_wave1, n_wave2, concurrency = 120, 80, 50
    n_requests = n_wave1 + n_wave2
    wave1_out, wave2_out = {}, {}

    def load_wave1():
        wave1_out.update(loadgen.run_load(
            engine, frames, n_requests=n_wave1,
            concurrency=concurrency, references=refs_old,
            alt_references=refs_new, timeout=120.0))

    def load_wave2():
        wave2_out.update(loadgen.run_load(
            engine, frames, n_requests=n_wave2,
            concurrency=concurrency, references=refs_new,
            timeout=120.0))

    try:
        with CompileWatch() as watch:
            loader1 = threading.Thread(target=load_wave1,
                                       name="drill-load-1")
            loader1.start()
            # Phase 1: let the old model serve a chunk of traffic, then
            # commit the good checkpoint and reload mid-stream.
            _await_metric(lambda: engine.metrics.responses, 30, 60,
                          "responses before good checkpoint")
            trainer.save(_ServeState(1, params_good))
            act = reloader.poll_once()
            assert act["action"] == "swapped", \
                f"good checkpoint did not swap: {act}"
            assert reloader.current_step == 1
            # Phase 2: serve wave 2 on the new model, then commit the
            # bad checkpoint — canary must catch it and roll back while
            # traffic keeps flowing.
            served_at_swap = engine.metrics.responses
            loader2 = threading.Thread(target=load_wave2,
                                       name="drill-load-2")
            loader2.start()
            _await_metric(lambda: engine.metrics.responses,
                          served_at_swap + 30, 60,
                          "responses after swap")
            trainer.save(_ServeState(2, params_bad))
            act = reloader.poll_once()
            assert act["action"] == "rolled_back", \
                f"bad checkpoint was not rolled back: {act}"
            assert "non-finite" in act["reason"], act["reason"]
            # Pinned: the same bad step is never retried.
            assert reloader.poll_once()["action"] == "none"
            loader1.join(180)
            loader2.join(180)
            assert not (loader1.is_alive() or loader2.is_alive()), \
                "load generator wedged"
    finally:
        reloader.stop()
        trainer.close()

    m = engine.metrics
    completed = wave1_out["completed"] + wave2_out["completed"]
    dropped = wave1_out["dropped"] + wave2_out["dropped"]
    mismatched = wave1_out["mismatched"] + wave2_out["mismatched"]
    print(f"  {completed}/{n_requests} responses at concurrency "
          f"{concurrency} across 1 swap + 1 rollback; wave 1: "
          f"{wave1_out['matched_primary']} old-model + "
          f"{wave1_out['matched_alt']} new-model matches, wave 2 "
          f"(post-swap): {wave2_out['matched_primary']} new-model "
          f"matches; reference = {ref_kind}")
    print("  metrics:", m.report())
    assert completed == n_requests, f"completed {completed}/{n_requests}"
    assert not dropped, f"dropped across reload: {dropped}"
    assert not mismatched, f"bit-incorrect responses: {mismatched}"
    # Both models actually served: wave 1's first 30 responses were
    # awaited on the old model before the checkpoint even existed, and
    # wave 2 ran entirely post-swap against the new model's references.
    assert wave1_out["matched_primary"] > 0, "no request served pre-swap"
    assert wave2_out["matched_primary"] == n_wave2, \
        "post-swap traffic did not all bit-match the new model"
    assert m.swaps == 1, f"swaps={m.swaps}, want exactly 1"
    assert m.rollbacks == 1, f"rollbacks={m.rollbacks}, want exactly 1"
    assert watch.compiles == 0, \
        f"{watch.compiles} fresh compile(s) across reload under load"
    # The engine serves the GOOD step's weights (bit-exact through the
    # orbax round-trip) and reports degraded (pinned rollback).
    for got, want in zip(
            jax.tree_util.tree_leaves(engine.predictor.variables["params"]),
            jax.tree_util.tree_leaves(params_good)):
        assert np.array_equal(np.asarray(got), np.asarray(want)), \
            "serving params are not the good checkpoint's"
    health = engine.health()
    assert health["state"] == "degraded" and health["ready"], health
    assert health["degraded_reasons"] == ["canary-rollback"], health

    # Phase 3: breaker proof on the same still-live engine (expected
    # output = the NEW model's, since the good swap is serving).
    _prove_breaker(engine, *frames[0], expected=refs_new[0])
    engine.close()
    assert engine.health()["state"] == "closed"


def drill_fleet(root):
    """3-replica fleet: kill a replica under 50 concurrent clients
    (zero dropped/bit-incorrect, breaker isolates it, router
    re-balances its buckets), then a rolling reload — exactly one
    canary, zero fresh compiles on the waved replicas — and a NaN
    checkpoint that rolls the whole fleet back."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from raft_tpu.checkpoint import RunCheckpointer
    from raft_tpu.serving import (CircuitBreaker, CompileWatch,
                                  FleetReloadConfig, FleetReloader,
                                  ServingConfig, loadgen, make_fleet)

    predictor = _make_predictor()
    frames = loadgen.make_frames(SHAPES, per_shape=2, seed=41)
    refs_old, ref_kind = _references(predictor, frames, max_batch=4)

    n_replicas, concurrency = 3, 50
    fleet = make_fleet(predictor, n_replicas, ServingConfig(
        max_batch=4, max_wait_ms=3.0, buckets=BUCKETS,
        breaker_threshold=2, breaker_cooldown_s=120.0))
    # Long cooldown: the killed replica must stay OPEN (unroutable) for
    # the rest of the drill instead of half-open probing its dead device.
    fleet.start(warm_spares=True)
    owned = sum(s["compiles"] for s in fleet.warmup_stats.values())
    spare = sum(s.get("spare_compiles", 0.0)
                for s in fleet.warmup_stats.values())
    assignments = fleet.assignments()
    print(f"  assignment: {assignments}; warmup compiles owned={owned:g} "
          f"spare={spare:g} (spares warm from the shared cache)")
    assert owned > 0, "owners compiled nothing"
    assert spare == 0, \
        f"spare warmups compiled {spare:g} times (shared cache broken)"
    victim = next(rid for rid, bs in assignments.items() if bs)
    victim_buckets = assignments[victim]

    # -- Phase 1: kill the victim under 50-client load ------------------
    n_requests = 150
    out1 = {}

    def load1():
        out1.update(loadgen.run_load(
            fleet, frames, n_requests=n_requests,
            concurrency=concurrency, references=refs_old, timeout=120.0))

    def fleet_responses():
        return sum(e.metrics.responses for e in fleet.engines.values())

    loader = threading.Thread(target=load1, name="fleet-load-1")
    loader.start()
    _await_metric(fleet_responses, 30, 120, "responses before kill")
    fleet.kill_replica(victim)
    loader.join(300)
    assert not loader.is_alive(), "load generator wedged"

    per = {rid: (s["completed"], s["dropped"])
           for rid, s in out1["per_replica"].items()}
    print(f"  kill {victim} under load: {out1['completed']}/{n_requests} "
          f"responses at concurrency {concurrency}, per-replica "
          f"(completed, dropped) = {per}; reference = {ref_kind}")
    print("  fleet:", fleet.metrics.report())
    assert out1["completed"] == n_requests, \
        f"completed {out1['completed']}/{n_requests}"
    assert not out1["dropped"], f"dropped: {out1['dropped']}"
    assert not out1["mismatched"], \
        f"bit-incorrect responses: {out1['mismatched']}"
    # Breaker isolation on the dead replica, traffic re-routed.
    v_eng = fleet.engines[victim]
    assert v_eng.breaker.state == CircuitBreaker.OPEN, \
        f"victim breaker {v_eng.breaker.state}, want open"
    assert v_eng.health()["state"] == "open"
    snap = fleet.metrics.snapshot()
    assert snap["fleet_failovers"] > 0, "no failover was ever recorded"
    assert snap["fleet_shed"] == 0, f"shed {snap['fleet_shed']} requests"
    # Router re-balance: every victim bucket has a new live owner.
    for b in victim_buckets:
        new_owner = fleet.effective_owner(b)
        assert new_owner is not None and new_owner != victim, \
            f"bucket {b} not re-balanced (owner {new_owner})"
    print(f"  victim {victim} OPEN; its buckets re-balanced to "
          f"{[fleet.effective_owner(b) for b in victim_buckets]}")
    health = fleet.health()
    assert health["ready"] and health["state"] == "degraded", health

    # -- Phase 2: rolling reload on the degraded fleet ------------------
    vars_cur = predictor.variables
    params_good = jax.tree_util.tree_map(
        lambda x: x * (1 + 1e-3), vars_cur["params"])
    params_bad = jax.tree_util.tree_map(
        lambda x: jnp.full_like(x, jnp.nan), vars_cur["params"])
    refs_new, _ = _references(
        predictor.clone_with_variables(
            dict(vars_cur, params=params_good)), frames, max_batch=4)

    class _FleetState:
        def __init__(self, step, params):
            self.step = jnp.asarray(step, jnp.int32)
            self.params = params
            self.batch_stats = vars_cur.get("batch_stats", {})
            self.opt_state = {"m": jnp.zeros(4, jnp.float32)}

    # Warm orbax's one-time internal jit against a scratch dir so the
    # zero-compile watch below measures only the serving path.
    scratch = RunCheckpointer(os.path.join(root, "scratch"))
    scratch.save(_FleetState(1, params_good))
    scratch.close()
    ckpt_dir = os.path.join(root, "ckpts")
    trainer = RunCheckpointer(ckpt_dir)
    reloader = FleetReloader(
        fleet, ckpt_dir, canary_frames=[frames[0]],
        config=FleetReloadConfig(canary_max_epe=50.0))
    try:
        trainer.save(_FleetState(1, params_good))
        with CompileWatch() as watch:
            act = reloader.poll_once()
        assert act["action"] == "swapped", f"reload did not swap: {act}"
        assert isinstance(act["canary_replica"], str), act
        # Exactly one canary; the dead replica is skipped, everyone
        # else waves; zero fresh compiles anywhere on the wave.
        assert act["skipped"] == [victim], act
        assert len(act["waved"]) == n_replicas - 2, act
        assert act["wave_compiles"] == 0, act
        assert watch.compiles == 0, \
            f"{watch.compiles} fresh compile(s) during rolling reload"
        print(f"  rolling reload: canary {act['canary_replica']} "
              f"(EPE {act['epe']:.3f} px), waved {act['waved']}, "
              f"skipped {act['skipped']}, 0 fresh compiles")
        # Post-reload traffic must bit-match the NEW model fleet-wide.
        out2 = loadgen.run_load(fleet, frames, n_requests=60,
                                concurrency=16, references=refs_new,
                                timeout=120.0)
        assert out2["completed"] == 60 and not out2["dropped"], out2
        assert not out2["mismatched"], \
            f"post-reload mismatches: {out2['mismatched']}"
        served_by = sorted(out2["per_replica"])
        assert victim not in served_by, \
            f"dead replica {victim} served post-reload traffic"
        print(f"  post-reload: 60/60 bit-exact on the new model, "
              f"served by {served_by}")

        # NaN checkpoint: canary catches it, whole fleet keeps the good
        # weights, step is pinned fleet-wide.
        trainer.save(_FleetState(2, params_bad))
        act = reloader.poll_once()
        assert act["action"] == "rolled_back", act
        assert "non-finite" in act["reason"], act
        assert reloader.poll_once()["action"] == "none", \
            "pinned step was retried"
        assert reloader.current_step == 1
        flow = fleet.submit(*frames[0]).result(60)
        assert np.array_equal(flow, refs_new[0]), \
            "post-rollback response not bit-exact vs the good model"
        print(f"  NaN checkpoint rolled back by canary "
              f"{act['canary_replica']}, step 2 pinned; fleet still "
              f"serves the good weights bit-exact")
    finally:
        reloader.stop()
        trainer.close()
        fleet.close()
    assert fleet.health()["state"] == "closed"


def drill_streaming(root):
    """3-replica fleet under N-stream session load: kill the replica
    most streams are pinned to mid-run — every affected stream drops
    its state, cold-restarts on another replica (honest extra encoder
    MISS) and keeps flowing: zero dropped responses, zero shed, and
    zero fresh XLA compiles anywhere (restart primes hit the shared
    executable cache)."""
    import numpy as np
    from collections import Counter

    from raft_tpu.serving import (CompileWatch, ServingConfig, loadgen,
                                  make_fleet)

    predictor = _make_predictor()
    n_streams, n_frames, shape = 6, 12, (36, 60)
    fleet = make_fleet(predictor, 3, ServingConfig(
        max_batch=4, max_wait_ms=3.0, warm_buckets=(shape,),
        warm_iters=1, breaker_threshold=2, breaker_cooldown_s=120.0))
    fleet.start()
    warm_compiles = sum(s["compiles"] for s in fleet.warmup_stats.values())
    # Sticky pins are deterministic (rendezvous over stream ids): the
    # victim is known before any traffic flows.
    pins = [fleet.router.owners_for_key(f"stream:load-{i}")[0]
            for i in range(n_streams)]
    victim, n_pinned = Counter(pins).most_common(1)[0]
    print(f"  pins: {dict(Counter(pins))}; victim {victim} "
          f"({n_pinned} streams); warmup compiles {warm_compiles:g} "
          f"(shared cache: every other replica warms for free)")
    assert n_pinned >= 1

    out = {}
    t_kill = [None]

    def load():
        out.update(loadgen.run_stream_load(
            fleet, n_streams, n_frames, shape=shape, timeout=120.0))

    def victim_responses():
        return fleet.engines[victim].metrics.responses

    try:
        with CompileWatch() as watch:
            loader = threading.Thread(target=load, name="stream-load")
            loader.start()
            _await_metric(victim_responses, 2, 120,
                          "victim responses before kill")
            fleet.kill_replica(victim)
            t_kill[0] = time.monotonic()
            loader.join(300)
            assert not loader.is_alive(), "stream load generator wedged"
    finally:
        fleet.close()

    sessions = {name: rec["session"]
                for name, rec in out["per_stream"].items()}
    failovers = sum(s["failovers"] for s in sessions.values())
    moved = [name for name, s in sessions.items()
             if s["failovers"] > 0]
    print(f"  kill {victim} mid-run: {out['steady_pairs']} steady pairs, "
          f"{out['dropped']} dropped, {failovers} stream failover(s) "
          f"({moved}), {watch.compiles} post-warmup compiles")
    print("  fleet:", fleet.metrics.report())
    assert out["dropped"] == 0, f"dropped {out['dropped']} responses"
    assert failovers >= 1, "no stream ever failed over"
    assert watch.compiles == 0, \
        f"{watch.compiles} fresh compile(s) — cold restarts must serve " \
        "through the shared executable cache"
    assert fleet.metrics.shed == 0, f"shed {fleet.metrics.shed}"
    expected_rate = (n_frames - 1) / n_frames
    for name, s in sessions.items():
        assert s["replica_id"] != victim, \
            f"{name} still pinned to the dead replica"
        if s["failovers"] == 0:
            # Untouched stream: exactly one prime MISS, perfect rate.
            assert s["encoder_misses"] == 1 and np.isclose(
                s["encoder_cache_hit_rate"], expected_rate), s
        else:
            # Restarted stream: the cold restart is an HONEST extra
            # MISS and an extra cold pair, never hidden by the stats.
            assert s["encoder_misses"] >= 2, s
            assert s["cold_pairs"] >= 2, s
    print(f"  all {n_streams} streams live off {victim}; untouched "
          f"streams at hit rate {expected_rate:.3f}, restarted ones "
          f"show their extra MISS")


def drill_brownout(root):
    """Burst LOW traffic past capacity against a quality-ladder engine:
    the brownout controller steps LOW down the pre-warmed iters ladder
    (every degraded response bit-matches exactly one level), HIGH never
    degrades, nothing is dropped, the engine recovers to full quality
    when the burst drains, and the whole episode compiles nothing."""
    import numpy as np

    from raft_tpu.serving import (CompileWatch, ServingConfig,
                                  ServingEngine, loadgen)
    from raft_tpu.utils.padder import InputPadder

    from raft_tpu.evaluate import load_predictor
    full_iters, ladder = 4, (2, 1)
    predictor = load_predictor("random", small=True, iters=full_iters)
    shape = (36, 60)
    frames = loadgen.make_frames([shape], per_shape=3, seed=53)

    engine = ServingEngine(predictor, ServingConfig(
        max_batch=4, max_wait_ms=3.0, buckets=(shape,),
        iters_ladder=ladder, brownout_high_water=5,
        brownout_low_water=1, brownout_dwell_ms=150.0))
    warm = engine.warmup()
    engine.start(warmup=False)
    ctl = engine.brownout
    warm_desc = ", ".join(f"{k}: {int(v['compiles'])}"
                          for k, v in warm.items())
    print(f"  warmup: {{bucket: compiles}} = {{{warm_desc}}}")
    assert len(warm) == 1 + len(ladder), \
        f"warmup covered {len(warm)} executables, want full + ladder"

    def _refs_at(iters):
        """Per-level references through the SAME warmed executables the
        engine serves from (bit-exact on any topology); full quality
        takes the legacy no-iters path, exactly like HIGH traffic."""
        refs = []
        for im1, im2 in frames:
            p = InputPadder(im1.shape, mode="sintel", factor=8)
            a, b = p.pad(im1, im2)
            s1 = np.repeat(a[None], 4, 0)
            s2 = np.repeat(b[None], 4, 0)
            out = (predictor.dispatch_batch(s1, s2)
                   if iters == full_iters
                   else predictor.dispatch_batch(s1, s2, iters=iters))
            refs.append(p.unpad(np.asarray(out[1])[0]))
        return refs

    n_low, n_high = 90, 16
    try:
        with CompileWatch() as watch:
            refs_by_iters = {lvl: _refs_at(lvl)
                             for lvl in (full_iters, *ladder)}
            # -- burst: 16 LOW clients (2x+ the sustainable closed-loop
            # load for one bucket) + a 2-client HIGH control lane.
            res = loadgen.run_overload(
                engine, frames, n_low=n_low, n_high=n_high,
                refs_by_iters=refs_by_iters, full_iters=full_iters,
                low_concurrency=16, high_concurrency=2, timeout=120.0)
            # -- recovery: the router keeps ticking the controller while
            # idle; hysteresis steps it back to full quality.
            deadline = time.monotonic() + 60.0
            while ctl.level > 0:
                if time.monotonic() >= deadline:
                    raise AssertionError(
                        f"brownout never recovered (level {ctl.level})")
                time.sleep(0.02)
            recovered = engine.submit(*frames[0],
                                      priority="low").result(60)
    finally:
        engine.close()

    stats = ctl.stats()
    degraded_served = sum(n for lvl, n in res["quality_counts"].items()
                          if lvl != full_iters)
    print(f"  burst: {res['completed']}/{n_low + n_high} responses "
          f"({res['throughput_rps']:.1f} req/s), LOW quality counts = "
          f"{res['quality_counts']}, HIGH p99 = "
          f"{res['latency_ms_high']['p99']:.0f} ms, LOW p99 = "
          f"{res['latency_ms_low']['p99']:.0f} ms")
    print(f"  controller: transitions={stats['transitions']}, "
          f"time_in_brownout={stats['time_in_brownout_s']:.2f}s, "
          f"recovered to level {stats['level']}")
    print("  metrics:", engine.metrics.report())
    assert res["completed"] == n_low + n_high, \
        f"completed {res['completed']}/{n_low + n_high}"
    assert res["dropped_low"] == 0 and res["dropped_high"] == 0, \
        (f"dropped before ladder exhaustion: low={res['dropped_low']} "
         f"high={res['dropped_high']}")
    assert res["high_degraded"] == 0, \
        f"{res['high_degraded']} HIGH responses were degraded"
    assert res["mismatched"] == 0, \
        f"{res['mismatched']} responses matched no quality level"
    assert degraded_served > 0, \
        f"ladder never engaged: quality counts {res['quality_counts']}"
    assert stats["transitions"] >= 2, \
        f"expected a down + up transition, got {stats['transitions']}"
    assert stats["time_in_brownout_s"] > 0
    # Served-quality histogram on the engine agrees with the client's
    # bit-exact classification (HIGH lane + full-quality LOW at full).
    hist = engine.metrics.quality_histogram()
    assert set(hist) <= {full_iters, *ladder}, hist
    assert np.array_equal(recovered, refs_by_iters[full_iters][0]), \
        "post-recovery LOW response is not full quality"
    assert watch.compiles == 0, \
        f"{watch.compiles} fresh XLA compile(s) during brownout"


def drill_pallas_kernels(root):
    """The whole fused-kernel chain forced at once — banded correlation
    (RAFT_CORR_BACKEND=pallas), the one-launch refine step
    (RAFT_STEP_PALLAS=1), and the component motion/GRU kernels it
    subsumes where it admits — warms up and serves bit-exactly with
    zero post-warmup compiles (the round-7 acceptance probe, extended
    round 10). Non-small model — the small model's encoder/GRU have no
    fused path — one bucket, small load: the subject is the trace-time
    flags riding the warmup contract, not throughput."""
    from raft_tpu.evaluate import load_predictor
    from raft_tpu.serving import (CompileWatch, ServingConfig,
                                  ServingEngine, loadgen)
    from raft_tpu.utils.envflags import forced_flag

    n_requests, concurrency = 12, 4
    with forced_flag("RAFT_CORR_BACKEND", "pallas"), \
            forced_flag("RAFT_STEP_PALLAS", "1"), \
            forced_flag("RAFT_MOTION_PALLAS", "1"), \
            forced_flag("RAFT_GRU_PALLAS", "1"):
        predictor = load_predictor("random", iters=2,
                                   alternate_corr=True)
        assert predictor.step_impl == "1", predictor.step_impl
        assert predictor.motion_impl == "1", predictor.motion_impl
        assert predictor.gru_impl == "1", predictor.gru_impl
        # (64, 96) bucket — the smallest smoke shape whose 4-level
        # pooled pyramid keeps every level nonzero, which the banded
        # corr kernel's VMEM-resident layout requires.
        frames = loadgen.make_frames([(64, 96), (61, 93)], per_shape=2,
                                     seed=23)
        refs, ref_kind = _references(predictor, frames, max_batch=2)

        engine = ServingEngine(predictor, ServingConfig(
            max_batch=2, max_wait_ms=3.0, buckets=((64, 96),)))
        warm = engine.warmup()
        engine.start(warmup=False)
        try:
            with CompileWatch() as watch:
                res = loadgen.run_load(engine, frames,
                                       n_requests=n_requests,
                                       concurrency=concurrency,
                                       references=refs)
        finally:
            engine.close()

    print(f"  {res['completed']}/{n_requests} responses with the full "
          f"fused-kernel chain forced; reference = {ref_kind}")
    warm_desc = ", ".join(f"{k}: {int(v['compiles'])}"
                          for k, v in warm.items())
    print(f"  warmup: {{bucket: compiles}} = {{{warm_desc}}}")
    assert res["completed"] == n_requests, \
        f"completed {res['completed']}/{n_requests}"
    assert not res["dropped"], f"dropped requests: {res['dropped']}"
    assert not res["mismatched"], \
        f"incorrect responses: {res['mismatched']}"
    assert all(v["compiles"] >= 1 for v in warm.values()), warm
    assert not watch.compiles, \
        f"{watch.compiles} fresh XLA compile(s) after warmup — the " \
        f"fused-kernel flags failed to bake into the bucket executables"
    assert engine.metrics.compiles == 0, engine.metrics.compiles


def drill_highres(root):
    """Spatially-sharded serving: mixed-traffic overlap on one engine
    (zero post-warmup compiles), then kill-under-load on a
    heterogeneous fleet — sharded requests fail over or shed cleanly,
    never wedge a stream."""
    import jax
    import numpy as np

    from raft_tpu.serving import (CompileWatch, EngineUnhealthy,
                                  ServingConfig, ServingEngine,
                                  ServingFleet, loadgen)

    if jax.device_count() < 4:
        raise AssertionError(
            f"highres drill needs >= 4 devices, have {jax.device_count()}"
            " — run via scripts/serve_drill.py (it forces the host-"
            "device env before jax initializes)")

    shards = 4
    highres = (64, 96)
    small_shapes = [(36, 60), (33, 57)]   # both pad to the (40,64) bucket
    predictor = _make_predictor()

    small_frames = loadgen.make_frames(small_shapes, per_shape=2, seed=71)
    hi_frames = loadgen.make_frames([highres], per_shape=2, seed=72)
    frames = small_frames + hi_frames
    refs, ref_kind = _references(predictor, small_frames, max_batch=4)

    base = dict(max_batch=4, max_wait_ms=3.0, buckets=tuple(small_shapes),
                sharded_buckets=(highres,), sharded_shards=shards,
                sharded_area_threshold=highres[0] * highres[1])

    # -- Part A: one engine, mixed highres + batch-1 traffic ------------
    engine = ServingEngine(predictor, ServingConfig(**base))
    mesh = engine._sharded_mesh
    # Sharded references come from the sharded executable itself: that
    # IS the bucket's contractual server (the unsharded executable is a
    # different float-accumulation order).
    for im1, im2 in hi_frames:
        out = predictor.sharded_dispatch(im1[None], im2[None], mesh=mesh)
        refs.append(np.asarray(out[1][0]))
    warm = engine.warmup()
    engine.start(warmup=False)
    try:
        mesh_bucket = next(k for k in warm if len(k) > 2
                           and k[2] == "mesh")
        with CompileWatch() as watch:
            res = loadgen.run_load(engine, frames, n_requests=48,
                                   concurrency=8, references=refs)
        streams = sorted(map(str, engine._streams))
    finally:
        engine.close()
    sharded_n = int(engine.metrics.snapshot()["serving_sharded_requests"])
    print(f"  mixed traffic: {res['completed']}/48 responses, "
          f"{sharded_n} sharded, batch histogram "
          f"{res['batch_histogram']}; reference = {ref_kind}")
    print(f"  dispatch streams: {streams}")
    assert res["completed"] == 48 and not res["dropped"], res["dropped"]
    assert not res["mismatched"], \
        f"bit-incorrect responses: {res['mismatched']}"
    assert sharded_n == 16, f"sharded_requests {sharded_n}, want 16"
    assert str(mesh_bucket) in streams, \
        f"sharded bucket {mesh_bucket} has no dedicated stream"
    assert len(streams) >= 2, \
        "sharded and batched traffic must run on separate streams"
    # Small traffic actually batched while sharded traffic ran batch-1:
    # the overlap is real, not serialized through one stream.
    assert any(k > 1 for k in res["batch_histogram"]), \
        f"no multi-request batch formed: {res['batch_histogram']}"
    assert watch.compiles == 0, \
        f"{watch.compiles} fresh XLA compile(s) under mixed traffic"
    print("  PART A: overlap + zero post-warmup compiles proved")

    # -- Part B: heterogeneous fleet, kill-under-load -------------------
    # r0/r1 host the mesh, r2 does not (the capacity-gate case: its
    # device set is imagined too small — here simply unconfigured).
    engines = []
    for rid in ("r0", "r1"):
        cfg = ServingConfig(replica_id=rid, breaker_threshold=2,
                            breaker_cooldown_s=120.0, **base)
        pred = (predictor if rid == "r0"
                else predictor.clone_with_variables(predictor.variables))
        engines.append(ServingEngine(pred, cfg))
    cfg2 = ServingConfig(replica_id="r2", breaker_threshold=2,
                         breaker_cooldown_s=120.0,
                         max_batch=4, max_wait_ms=3.0,
                         buckets=tuple(small_shapes))
    engines.append(ServingEngine(
        predictor.clone_with_variables(predictor.variables), cfg2))
    fleet = ServingFleet(engines)
    fleet.start()
    try:
        mesh_bucket = engines[0].sharded_route((*highres, 3))
        owner = fleet.effective_owner(mesh_bucket)
        assert owner in ("r0", "r1"), owner

        n_requests = 90
        out = {}

        def load():
            out.update(loadgen.run_load(
                fleet, frames, n_requests=n_requests, concurrency=8,
                references=refs, timeout=120.0))

        def responses():
            return sum(e.metrics.responses
                       for e in fleet.engines.values())

        loader = threading.Thread(target=load, name="highres-load")
        loader.start()
        _await_metric(responses, 20, 120, "responses before kill")
        fleet.kill_replica(owner)
        loader.join(300)
        assert not loader.is_alive(), "load generator wedged"

        survivor = fleet.effective_owner(mesh_bucket)
        per = {rid: (s["completed"], s["dropped"])
               for rid, s in out["per_replica"].items()}
        print(f"  kill {owner} under load: {out['completed']}/"
              f"{n_requests} responses, per-replica = {per}; sharded "
              f"owner now {survivor}")
        assert out["completed"] == n_requests, \
            f"completed {out['completed']}/{n_requests}"
        assert not out["dropped"], f"dropped: {out['dropped']}"
        assert not out["mismatched"], \
            f"bit-incorrect responses: {out['mismatched']}"
        assert survivor in ("r0", "r1") and survivor != owner, survivor
        snap = fleet.metrics.snapshot()
        assert snap["fleet_failovers"] > 0, "no failover recorded"
        # Sharded traffic never lands on the mesh-less replica.
        f = fleet.submit(*hi_frames[0])
        flow = f.result(60)
        assert f.replica_id == survivor, \
            f"sharded request served by {f.replica_id}, want {survivor}"
        assert np.array_equal(flow, refs[len(small_frames)]), \
            "post-failover sharded response not bit-exact"

        # Both mesh replicas dead: sharded requests shed CLEANLY with
        # an error naming the mesh; small traffic still flows on r2.
        fleet.kill_replica(survivor)
        # The kill is quiet — the health gate flips only once dispatches
        # fail. Drive the threshold-2 breaker open with sharded traffic:
        # every attempt surfaces an error promptly (never wedges).
        for _ in range(4):
            f = fleet.submit(*hi_frames[0])
            err = None
            try:
                f.result(60)
            except Exception as e:
                err = e
            assert err is not None, \
                "dead mesh replica served a sharded request"
            if fleet.effective_owner(mesh_bucket) is None:
                break
        assert fleet.effective_owner(mesh_bucket) is None, \
            "dead mesh replica still routable after breaker threshold"
        f = fleet.submit(*hi_frames[0])
        try:
            f.result(60)
            raise AssertionError("sharded request served with no mesh-"
                                 "capable replica alive")
        except EngineUnhealthy as e:
            assert "mesh" in str(e), e
            print(f"  clean shed with both mesh replicas dead: {e}")
        f = fleet.submit(*small_frames[0])
        flow = f.result(60)
        assert f.replica_id == "r2" and np.array_equal(flow, refs[0])
        print("  PART B: failover + clean shed proved (r2 still serves "
              "small traffic)")
    finally:
        fleet.close()


def drill_wire(root):
    """Mixed uint8/float32 wire traffic against a 3-replica fleet with
    a mid-load replica kill: zero dropped, zero bit-incorrect, zero
    post-warmup compiles; uint8 and integral-float32 bit-identical;
    low_res responses bit-match the reference 1/8 grid."""
    import numpy as np

    from raft_tpu.serving import (CompileWatch, ServingConfig, loadgen,
                                  make_fleet, upsample_flow)
    from raft_tpu.utils.padder import InputPadder

    predictor = _make_predictor()
    # Three traffic classes over the same shapes: uint8 (the u8 wire),
    # the SAME values as float32 (integral -> auto-detected back onto
    # the u8 wire), and fresh non-integral float32 (the f32 wire).
    frames_u8 = loadgen.make_frames(SHAPES, per_shape=2, seed=71)
    frames_f32i = [(a.astype(np.float32), b.astype(np.float32))
                   for a, b in frames_u8]
    frames_f32n = loadgen.make_frames(SHAPES, per_shape=1, seed=72,
                                      dtype=np.float32)
    refs_u8, ref_kind = _references(predictor, frames_u8, max_batch=4)
    refs_f32i, _ = _references(predictor, frames_f32i, max_batch=4)
    refs_f32n, _ = _references(predictor, frames_f32n, max_batch=4)
    # The wire contract's foundation, proved before any serving runs:
    # integral inputs produce bit-identical flow on either wire dtype.
    for k, (ru, rf) in enumerate(zip(refs_u8, refs_f32i)):
        assert np.array_equal(ru, rf), \
            f"pair {k}: uint8 vs integral-float32 references differ"
    print(f"  {len(refs_u8)} uint8 vs integral-float32 reference pairs "
          f"bit-identical; reference = {ref_kind}")

    mixed = frames_u8 + frames_f32i + frames_f32n
    # Integral float32 pairs must serve the u8-wire answer — which the
    # reference check above just proved equals their own.
    refs = refs_u8 + refs_f32i + refs_f32n

    n_replicas, concurrency, n_requests = 3, 50, 150
    fleet = make_fleet(predictor, n_replicas, ServingConfig(
        max_batch=4, max_wait_ms=3.0, buckets=BUCKETS,
        breaker_threshold=2, breaker_cooldown_s=120.0))
    fleet.start(warm_spares=True)
    victim = next(rid for rid, bs in fleet.assignments().items() if bs)
    try:
        out = {}

        def load():
            out.update(loadgen.run_load(
                fleet, mixed, n_requests=n_requests,
                concurrency=concurrency, references=refs, timeout=120.0))

        def fleet_responses():
            return sum(e.metrics.responses
                       for e in fleet.engines.values())

        with CompileWatch() as watch:
            loader = threading.Thread(target=load, name="wire-load")
            loader.start()
            _await_metric(fleet_responses, 30, 120,
                          "responses before kill")
            fleet.kill_replica(victim)
            loader.join(300)
            assert not loader.is_alive(), "load generator wedged"
        print(f"  kill {victim} under mixed-dtype load: "
              f"{out['completed']}/{n_requests} responses at "
              f"concurrency {concurrency}")
        assert out["completed"] == n_requests, \
            f"completed {out['completed']}/{n_requests}"
        assert not out["dropped"], f"dropped: {out['dropped']}"
        assert not out["mismatched"], \
            f"bit-incorrect responses: {out['mismatched']}"
        assert watch.compiles == 0, \
            f"{watch.compiles} fresh compile(s) under mixed wire traffic"
        staged = sum(e.metrics.snapshot()["serving_staged_bytes"]
                     for e in fleet.engines.values())
        print(f"  0 dropped, 0 mismatched, 0 compiles; fleet staged "
              f"{staged / 1e6:.2f} MB for {n_requests} mixed requests")
        assert staged > 0, "staged-bytes accounting recorded nothing"

        # low_res: the 1/8-grid response bit-matches the reference
        # low-res flow and host-upsamples back to the frame shape.
        im1, im2 = frames_u8[0]
        padder = InputPadder(im1.shape, mode="sintel", factor=8)
        p1, p2 = padder.pad(im1, im2)
        ref_low, _ = predictor.predict_batch(
            np.repeat(p1[None], 4, axis=0), np.repeat(p2[None], 4, axis=0))
        lo = fleet.submit(im1, im2, low_res=True).result(60)
        assert np.array_equal(lo, ref_low[0]), \
            "low_res response does not bit-match the reference low flow"
        up = upsample_flow(lo, padder=padder)
        assert up.shape == (*im1.shape[:2], 2), up.shape
        print(f"  low_res: {lo.shape} bit-exact, host-upsampled to "
              f"{up.shape}")
    finally:
        fleet.close()


def drill_trace(root):
    """Tracing ON under the full traffic mix (batched + LOW burst +
    fleet kill + streams): /tmp/raft_trace.json is well-formed Chrome
    trace JSON, every request root span closes, failover hops are
    visible, and zero post-warmup compiles with tracing enabled."""
    import json

    from raft_tpu.observability import disable_tracing, enable_tracing
    from raft_tpu.serving import (CompileWatch, ServingConfig,
                                  ServingEngine, loadgen, make_fleet)

    trace_path = "/tmp/raft_trace.json"
    # Enabled BEFORE any engine exists: engines capture the tracer at
    # __init__, never retroactively.
    tracer = enable_tracing()
    try:
        predictor = _make_predictor()
        # -- Phase A: brownout-ladder engine, batched HIGH + LOW burst.
        a_shapes = [(36, 60), (33, 57)]   # one shared (40, 64) bucket
        a_frames = loadgen.make_frames(a_shapes, per_shape=2, seed=83)
        a_refs, ref_kind = _references(predictor, a_frames, max_batch=4)
        engine = ServingEngine(predictor, ServingConfig(
            max_batch=4, max_wait_ms=3.0, buckets=(a_shapes[0],),
            iters_ladder=(1,), brownout_high_water=4,
            brownout_low_water=1, brownout_dwell_ms=50.0,
            slo_ms=(("high", 5000.0), ("low", 10000.0))))
        assert engine._tracer is tracer, \
            "engine did not capture the enabled tracer at init"
        engine.warmup()

        # -- Phase B: 3-replica fleet for the injected failover + streams.
        b_frames = loadgen.make_frames(SHAPES, per_shape=2, seed=84)
        b_refs, _ = _references(predictor, b_frames, max_batch=4)
        stream_shape = (36, 60)
        fleet = make_fleet(predictor, 3, ServingConfig(
            max_batch=4, max_wait_ms=3.0, buckets=BUCKETS,
            warm_buckets=(stream_shape,), warm_iters=1,
            breaker_threshold=2, breaker_cooldown_s=120.0))
        fleet.start(warm_spares=True)
        victim = next(rid for rid, bs in fleet.assignments().items()
                      if bs)

        engine.start(warmup=False)
        with CompileWatch() as watch:
            # Phase A traffic: closed-loop HIGH load with bit-exact
            # references, then a fire-at-once LOW burst deep enough to
            # dwell past the brownout high-water mark.
            res_a = loadgen.run_load(engine, a_frames, n_requests=40,
                                     concurrency=8, references=a_refs)
            burst = [engine.submit(*a_frames[i % len(a_frames)],
                                   priority="low") for i in range(36)]
            for f in burst:
                f.result(120)   # completion only; LOW may be degraded
            engine.close()

            # Phase B traffic: kill the victim bucket-owner mid-load —
            # the re-dispatches are the injected failover hops.
            out_b = {}

            def load_b():
                out_b.update(loadgen.run_load(
                    fleet, b_frames, n_requests=90, concurrency=16,
                    references=b_refs, timeout=120.0))

            loader = threading.Thread(target=load_b, name="trace-load")
            loader.start()
            _await_metric(
                lambda: sum(e.metrics.responses
                            for e in fleet.engines.values()),
                20, 120, "fleet responses before kill")
            fleet.kill_replica(victim)
            loader.join(300)
            assert not loader.is_alive(), "load generator wedged"
            # Streaming sessions on the degraded fleet: warm-start /
            # prime / serialize spans land on the same timeline.
            res_s = loadgen.run_stream_load(fleet, n_streams=2,
                                            n_frames=6,
                                            shape=stream_shape,
                                            timeout=120.0)
            fleet.close()

        assert res_a["completed"] == 40 and not res_a["mismatched"], \
            f"phase A: {res_a['completed']}/40 completed, " \
            f"mismatched {res_a['mismatched']}"
        assert out_b["completed"] == 90 and not out_b["dropped"], \
            f"phase B: completed {out_b.get('completed')}, " \
            f"dropped {out_b.get('dropped')}"
        assert not out_b["mismatched"], \
            f"bit-incorrect under tracing: {out_b['mismatched']}"
        assert res_s["dropped"] == 0, f"streams dropped {res_s['dropped']}"
        assert watch.compiles == 0, \
            f"{watch.compiles} fresh XLA compile(s) after warmup with " \
            "tracing enabled — tracing perturbed the executable cache"

        # Every opened root span resolved (engines closed above).
        assert tracer.open_flows() == [], \
            f"unclosed request spans: {tracer.open_flows()}"
        assert tracer.dropped == 0, \
            f"ring overflowed ({tracer.dropped} dropped) at default " \
            "capacity — the drill should fit comfortably"

        written = tracer.write(trace_path)
        with open(written) as f:
            doc = json.load(f)
        assert isinstance(doc, dict) and isinstance(
            doc.get("traceEvents"), list) and doc["traceEvents"], \
            "trace artifact is not Chrome trace-event JSON"
        assert "dropped_events" in doc.get("otherData", {}), doc.keys()
        for ev in doc["traceEvents"]:
            need = ({"name", "ph"} if ev.get("ph") == "M"
                    else {"name", "ph", "ts"})   # metadata has no ts
            assert need <= set(ev), f"malformed event {ev}"
            assert "_seq" not in ev, "internal ring bookkeeping leaked"
        names = {ev["name"] for ev in doc["traceEvents"]}
        for want in ("request", "fleet_request", "queue", "dispatch",
                     "pad", "stack", "sync", "unpad", "xla_compile"):
            assert want in names, f"no '{want}' slice in the trace"
        # The artifact itself balances: per async id, begins == ends.
        open_by_id = {}
        for ev in doc["traceEvents"]:
            if ev["ph"] in ("b", "e"):
                k = (ev.get("cat"), ev["name"], ev.get("id"))
                open_by_id[k] = open_by_id.get(k, 0) + (
                    1 if ev["ph"] == "b" else -1)
        unbalanced = {k: v for k, v in open_by_id.items() if v}
        assert not unbalanced, f"unbalanced async spans: {unbalanced}"
        hops = sum(ev["name"] == "failover_hop"
                   for ev in doc["traceEvents"])
        assert hops >= 1, "replica kill produced no failover_hop events"
        n_roots = sum(ev["ph"] == "b" and ev["name"] == "request"
                      for ev in doc["traceEvents"])
        statuses = sorted({ev.get("args", {}).get("status")
                           for ev in doc["traceEvents"]
                           if ev["ph"] == "e" and ev["name"] == "request"})
        brownout_evs = sum(ev.get("cat") == "brownout"
                           for ev in doc["traceEvents"])
        print(f"  {len(doc['traceEvents'])} events -> {written} "
              f"({tracer.recorded} recorded, 0 dropped); reference = "
              f"{ref_kind}")
        print(f"  {n_roots} request root spans (statuses {statuses}), "
              f"{hops} failover hop(s), {brownout_evs} brownout "
              f"event(s), {res_s['steady_pairs']} steady stream pairs, "
              f"0 post-warmup compiles")
    finally:
        # Process-global: later drills in an --drill all run must come
        # up untraced (engines capture at init).
        disable_tracing()


def drill_contbatch(root):
    """Kill a continuous-batching engine under mixed-iters load: every
    request accepted before close() resolves to a correct flow (0
    dropped — occupied slots finish, queued admissions drain), every
    post-close submit is a clean refusal, and the whole episode —
    admits, chunked steps, early-exit retires, the drain — compiles
    nothing post-warmup."""
    import threading

    import numpy as np

    from raft_tpu.evaluate import load_predictor
    from raft_tpu.serving import (CompileWatch, ServingConfig,
                                  ServingEngine, loadgen)
    from raft_tpu.utils.padder import InputPadder

    full_iters, ladder = 4, (2, 1)
    levels = [full_iters, *ladder]
    predictor = load_predictor("random", small=True, iters=full_iters)
    # Early exit live (loose tolerance): retires must free slots before
    # their assigned budget, or the drill is not exercising the thing
    # continuous batching exists for.
    predictor.early_exit = (5.0, 1)
    shape = (36, 60)
    frames = loadgen.make_frames([shape], per_shape=3, seed=67,
                                 dtype=np.float32)

    # On the continuous path EVERY request runs the early-exit-enabled
    # step family, full quality included, so all references go through
    # the iters executables (matches to float-accumulation noise, not
    # bit-exactly — chunked scan + separate finalize fuse differently).
    def _refs_at(iters):
        refs = []
        for im1, im2 in frames:
            p = InputPadder(im1.shape, mode="sintel", factor=8)
            a, b = p.pad(im1, im2)
            s1 = np.repeat(a[None], 4, 0)
            s2 = np.repeat(b[None], 4, 0)
            out = predictor.dispatch_batch(s1, s2, iters=iters)
            refs.append(p.unpad(np.asarray(out[1])[0]))
        return refs

    refs_by_iters = {lvl: _refs_at(lvl) for lvl in levels}

    engine = ServingEngine(predictor, ServingConfig(
        max_batch=4, max_wait_ms=3.0, buckets=(shape,),
        iters_ladder=ladder, continuous=True, contbatch_steps=1))
    warm = engine.warmup()
    engine.start(warmup=False)
    assert engine.contbatch is not None, "continuous scheduler not built"
    warm_desc = ", ".join(f"{k}: {int(v['compiles'])}"
                          for k, v in warm.items())
    print(f"  warmup: {{bucket: compiles}} = {{{warm_desc}}}")
    assert any(len(k) > 2 and k[2] == "cont" for k in warm), \
        f"warmup never touched the continuous step family: {list(warm)}"

    lock = threading.Lock()
    counter = [0]
    accepted = []            # (frame_idx, level, future)
    refused = [0]

    def pump():
        """Closed-loop client: submit, record the future, wait for it,
        repeat — exits on the first refusal (the engine closed)."""
        while True:
            with lock:
                i = counter[0]
                counter[0] += 1
            im1, im2 = frames[i % len(frames)]
            lvl = levels[i % len(levels)]
            try:
                fut = engine.submit(im1, im2, iters=lvl)
            except Exception:
                with lock:
                    refused[0] += 1
                return
            with lock:
                accepted.append((i % len(frames), lvl, fut))
            try:
                fut.result(120)
            except Exception:
                return          # graded below via the accepted list

    try:
        with CompileWatch() as watch:
            pumps = [threading.Thread(target=pump,
                                      name=f"contkill-{t}")
                     for t in range(8)]
            for th in pumps:
                th.start()
            # Let the slot table fill and cycle, then kill mid-flight.
            deadline = time.monotonic() + 10.0
            while engine.contbatch.occupied() == 0:
                if time.monotonic() >= deadline:
                    raise AssertionError(
                        "slot table never became occupied under load")
                time.sleep(0.005)
            with lock:
                in_flight = sum(not f.done() for _, _, f in accepted)
            load_at_kill = engine.contbatch.load()
            engine.close()
            for th in pumps:
                th.join(120)
    finally:
        engine.close()

    dropped = 0
    worst = 0.0
    for fi, lvl, fut in accepted:
        try:
            flow = fut.result(0)
        except Exception:
            dropped += 1
            continue
        ref = refs_by_iters[lvl][fi]
        epe = float(np.sqrt(((flow - ref) ** 2).sum(-1)).mean())
        worst = max(worst, epe)
    snap = engine.metrics.snapshot()
    print(f"  kill: {len(accepted)} accepted ({in_flight} unresolved "
          f"at close, scheduler load {load_at_kill}), "
          f"{refused[0]} clean post-close refusals")
    print(f"  drain: dropped={dropped}, worst EPE={worst:.2e}, "
          f"admits={int(snap['serving_contbatch_admits'])}, "
          f"retires={int(snap['serving_contbatch_retires'])}, "
          f"freed_iters={int(snap['serving_contbatch_freed_iters'])}")
    assert engine.health_state() == "closed", engine.health_state()
    assert load_at_kill > 0, "close() did not land under load"
    assert refused[0] == 8, \
        f"every pump must end on one clean refusal, got {refused[0]}"
    assert dropped == 0, f"{dropped} accepted requests dropped by close"
    assert accepted, "no requests accepted before the kill"
    assert worst <= 1e-4, f"worst EPE {worst} vs iters-path references"
    assert snap["serving_contbatch_admits"] == \
        snap["serving_contbatch_retires"], \
        (f"slots leaked: admits {snap['serving_contbatch_admits']} != "
         f"retires {snap['serving_contbatch_retires']}")
    assert snap["serving_contbatch_freed_iters"] > 0, \
        "early exit never freed a slot-iteration under this tolerance"
    assert watch.compiles == 0, \
        f"{watch.compiles} fresh XLA compile(s) during the episode"


def drill_gateway(root):
    """3 worker PROCESSES behind the gateway: SIGKILL one under load ->
    0 dropped / 0 bit-incorrect, supervised respawn with backoff,
    rejoin only after warmup + step sync, 0 post-warmup compiles."""
    import signal as signal_mod

    import numpy as np

    from raft_tpu.serving import loadgen
    from raft_tpu.serving.gateway import (GatewayConfig, ServingGateway,
                                          SocketTransport)
    from raft_tpu.serving.health import is_routable
    from raft_tpu.serving.netproto import FileLeaseStore
    from raft_tpu.serving.supervisor import WorkerSpec, WorkerSupervisor
    from raft_tpu.serving.worker import WorkerConfig

    STEP = 0
    lease_dir = os.path.join(root, "leases")
    store = FileLeaseStore(lease_dir)
    # Every worker serves every bucket so each rendezvous chain has two
    # live failover targets behind its owner.
    specs = [WorkerSpec(f"w{i}", WorkerConfig(
        worker_id=f"w{i}", lease_dir=lease_dir, buckets=BUCKETS,
        max_batch=4, max_wait_ms=3.0, queue_timeout_ms=60_000,
        step=STEP).to_dict()) for i in range(3)]
    sup = WorkerSupervisor(
        specs, store, stale_after_s=3.0,
        lease_grace_s=300.0,        # child startup = imports + warmup
        poll_interval_s=0.25, respawn_base_delay_s=0.25,
        respawn_max_delay_s=2.0, min_uptime_s=2.0)
    gw = ServingGateway(store, GatewayConfig(
        queue_timeout_ms=120_000, lease_ttl_s=2.0,
        poll_interval_s=0.1, dispatch_threads=CONCURRENCY,
        expected_step=STEP))
    sup.attach_registry(gw.registry)
    sup.start_all()
    sup.start()
    gw.start()
    try:
        _await_metric(lambda: len(gw.live_workers()), 3, 300.0,
                      "routable worker processes")
        print(f"  3 workers routable: {gw.live_workers()}")

        # Parent-side ground truth: load_predictor("random") is
        # deterministic (PRNGKey(0)), so parent and workers hold
        # bit-identical weights; same topology (env-inherited) + same
        # executable shapes => bit-identical flow across processes.
        predictor = _make_predictor()
        frames = loadgen.make_frames(SHAPES, per_shape=2, seed=23)
        refs, ref_kind = _references(predictor, frames, max_batch=4)

        killed = {}

        def killer():
            # Mid-load: wait for real traffic, then SIGKILL whichever
            # worker has served the most (maximizing in-flight damage).
            _await_metric(lambda: gw.metrics.responses, 5, 120.0,
                          "responses before kill")
            victim = gw.metrics.routed.most_common(1)[0][0]
            pid = store.read_all()[victim].pid
            os.kill(pid, signal_mod.SIGKILL)
            killed["victim"], killed["pid"] = victim, pid
            print(f"  SIGKILLed {victim} (pid {pid}) mid-load",
                  flush=True)

        kt = threading.Thread(target=killer, daemon=True)
        kt.start()
        res = loadgen.run_load(gw, frames, n_requests=N_REQUESTS,
                               concurrency=CONCURRENCY,
                               references=refs, timeout=600.0)
        kt.join(timeout=120.0)
        assert "victim" in killed, "kill thread never fired"
        victim, old_pid = killed["victim"], killed["pid"]

        print(f"  {res['completed']}/{N_REQUESTS} responses through "
              f"the kill; reference = {ref_kind}")
        print(f"  gateway: {gw.metrics.snapshot()}")
        assert res["completed"] == N_REQUESTS, \
            f"completed {res['completed']}/{N_REQUESTS}"
        assert not res["dropped"], f"dropped: {res['dropped']}"
        assert not res["mismatched"], \
            f"bit-incorrect responses: {res['mismatched']}"

        # Supervised respawn with backoff...
        _await_metric(lambda: sup.respawns(victim), 1, 120.0,
                      f"supervised respawn of {victim}")
        # ...and rejoin ONLY through warming -> routable + step sync
        # (the gateway refuses 'warming' and wrong-step leases, so
        # appearing in live_workers proves both gates passed).
        seen_states = set()

        def victim_live():
            lease = store.read_all().get(victim)
            if lease is not None:
                seen_states.add(lease.state)
            return 1 if victim in gw.live_workers() else 0

        _await_metric(victim_live, 1, 300.0,
                      f"{victim} rejoining the routable set")
        lease = store.read_all()[victim]
        assert lease.pid != old_pid, "victim lease not from respawn"
        assert lease.step == STEP, \
            f"rejoined at step {lease.step}, fleet at {STEP}"
        assert is_routable(lease.state), lease.state
        assert "warming" in seen_states, \
            "victim never showed 'warming' before rejoining " \
            f"(saw {seen_states})"
        # The respawned process answers at the right step on the wire.
        ping = SocketTransport().request(tuple(lease.addr),
                                         {"op": "ping"})[0]
        assert ping["status"] == "ok" and ping["step"] == STEP, ping
        print(f"  {victim} respawned (pid {lease.pid}), rejoined "
              f"routable at step {lease.step}; states seen: "
              f"{sorted(seen_states)}")

        # Zero post-warmup compiles — asserted CROSS-PROCESS via each
        # worker's own lease-published compile counter.
        for wid, l in sorted(store.read_all().items()):
            compiles = l.extra.get("post_warmup_compiles")
            assert compiles == 0, \
                f"{wid} reports {compiles} post-warmup compile(s)"

        # The kill must have surfaced as post-acceptance retries (the
        # victim had pooled connections and in-flight requests).
        assert sum(gw.metrics.retries.values()) >= 1, \
            "SIGKILL produced no gateway retries"

        # A second wave with the respawned worker in rotation.
        res2 = loadgen.run_load(gw, frames, n_requests=20,
                                concurrency=4, references=refs,
                                timeout=300.0)
        assert res2["completed"] == 20 and not res2["dropped"] \
            and not res2["mismatched"], res2
        print(f"  post-respawn wave: {res2['completed']}/20 clean; "
              f"served by {sorted(res2['per_replica'])}")

        # Per-worker liveness/respawn/retry gauges in the Prometheus
        # export (the PR-14 registry surface).
        txt = gw.registry.prometheus_text()
        for needle in (f'gateway_worker_live{{worker="{victim}"}}',
                       f'gateway_worker_respawns{{worker="{victim}"}}',
                       f'gateway_worker_up{{worker="{victim}"}}',
                       f'gateway_retries{{worker="{victim}"}}',
                       "gateway_workers_live"):
            assert needle in txt, f"{needle!r} missing from export"
        print("  prometheus export carries per-worker liveness/"
              "respawn/retry gauges")
    finally:
        gw.close()
        sup.stop(kill_workers=True)


def drill_autoscale(root):
    """Self-healing capacity end to end: burst load against a 1-worker
    fleet -> the autoscaler spawns a second worker PROCESS (unroutable
    until its lease proves warmup, brownout covering the gap on the
    incumbent); a partition-injected worker loses its requests to
    failover (hop stall, not client timeout); load drops -> the
    autoscaler drains the least-loaded worker gracefully (in-flight
    finishes, lease removed, exit 0, NO respawn). Gates: 0 dropped, 0
    bit-incorrect, 0 post-warmup compiles on every survivor."""
    import json

    from raft_tpu.serving import loadgen
    from raft_tpu.serving.autoscaler import Autoscaler, AutoscalerConfig
    from raft_tpu.serving.gateway import GatewayConfig, ServingGateway
    from raft_tpu.serving.netproto import FileLeaseStore
    from raft_tpu.serving.supervisor import WorkerSpec, WorkerSupervisor
    from raft_tpu.serving.worker import WorkerConfig

    STEP = 0
    lease_dir = os.path.join(root, "leases")
    store = FileLeaseStore(lease_dir)

    def _worker_cfg(wid):
        # Brownout ladder on every worker: while a scale-up is still
        # warming, the incumbent degrades LOW quality instead of
        # queue-timing anyone out. HIGH traffic (this drill's load)
        # stays bit-exact by the brownout contract.
        return WorkerConfig(
            worker_id=wid, lease_dir=lease_dir, buckets=BUCKETS,
            max_batch=4, max_wait_ms=3.0, queue_timeout_ms=60_000,
            step=STEP, iters_ladder=(1,), brownout_high_water=3,
            brownout_low_water=1, brownout_dwell_ms=150.0).to_dict()

    sup = WorkerSupervisor(
        [WorkerSpec("w0", _worker_cfg("w0"))], store,
        stale_after_s=3.0, lease_grace_s=300.0, poll_interval_s=0.25,
        respawn_base_delay_s=0.25, respawn_max_delay_s=2.0,
        min_uptime_s=2.0)
    gw = ServingGateway(store, GatewayConfig(
        queue_timeout_ms=120_000, lease_ttl_s=2.0, poll_interval_s=0.1,
        dispatch_threads=CONCURRENCY, expected_step=STEP,
        hop_timeout_s=1.5))
    sup.attach_registry(gw.registry)

    minted = []

    def spec_factory():
        # "scale0" vs "w0" splits the two padded buckets' rendezvous
        # ownership (w0 owns 40x64, scale0 owns 56x80) — the scaled-up
        # worker MUST own primary traffic or the partition leg never
        # arms its injector.
        wid = f"scale{len(minted)}"
        minted.append(wid)
        # The first scaled-up worker carries the partition injector:
        # its first accepted request blackholes for 4s — longer than
        # the gateway's 1.5s hop stall, shorter than any client
        # budget. spawn_worker treats env as a full REPLACEMENT, so
        # merge over the parent environment (JAX_PLATFORMS et al).
        env = (dict(os.environ, RAFT_FAULT_WORKER_PARTITION_S="4.0")
               if len(minted) == 1 else None)
        return WorkerSpec(wid, _worker_cfg(wid), env=env)

    auto = Autoscaler(sup, store, gw.registry, spec_factory,
                      AutoscalerConfig(
                          min_workers=1, max_workers=2,
                          high_water=1.5, low_water=0.5,
                          dwell_s=1.0, scale_up_cooldown_s=5.0,
                          scale_down_cooldown_s=10.0, lease_ttl_s=2.0))
    sup.start_all()
    sup.start()
    gw.start()
    try:
        _await_metric(lambda: len(gw.live_workers()), 1, 300.0,
                      "the initial worker becoming routable")
        predictor = _make_predictor()
        frames = loadgen.make_frames(SHAPES, per_shape=2, seed=29)
        refs, ref_kind = _references(predictor, frames, max_batch=4)

        # -- Phase 1: burst against one worker -> scale-up -------------
        n_burst, burst_conc = 80, 12
        out1 = {}

        def load1():
            out1.update(loadgen.run_load(
                gw, frames, n_requests=n_burst,
                concurrency=burst_conc, references=refs, timeout=600.0))

        loader = threading.Thread(target=load1, name="autoscale-burst")
        loader.start()
        # Drive the control loop at drill pace while the burst runs:
        # pressure (gateway queue depth / routable + lease-reported
        # engine load) must cross the high watermark and spawn exactly
        # one worker (max_workers=2 turns further desire into at-max).
        deadline = time.monotonic() + 120.0
        while auto.stats()["scale_ups"] == 0:
            if time.monotonic() >= deadline:
                raise AssertionError(
                    "burst never drove a scale-up (signals "
                    f"{auto.signals()})")
            auto.poll_once()
            time.sleep(0.25)
        print(f"  scale-up under burst: target "
              f"{auto.target_workers}, signals {auto.signals()}")
        loader.join(600)
        assert not loader.is_alive(), "burst load generator wedged"
        assert out1["completed"] == n_burst, \
            f"completed {out1['completed']}/{n_burst}"
        assert not out1["dropped"], f"dropped: {out1['dropped']}"
        assert not out1["mismatched"], \
            f"bit-incorrect responses: {out1['mismatched']}"
        assert auto.stats()["scale_ups"] == 1, auto.stats()
        assert "scale0" in sup.worker_ids(), sup.worker_ids()
        # Brownout covered the warmup gap on the incumbent: its
        # controller provably engaged while the burst outran capacity.
        w0_lease = store.read_all()["w0"]
        assert w0_lease.extra.get("brownout_transitions", 0) >= 1, \
            (f"brownout never engaged on w0 during the burst: "
             f"{w0_lease.extra}")
        print(f"  burst: {out1['completed']}/{n_burst} bit-exact at "
              f"concurrency {burst_conc}; w0 brownout transitions = "
              f"{w0_lease.extra['brownout_transitions']}; reference = "
              f"{ref_kind}")

        # -- Phase 2: the scale-up joins routing only after warmup ----
        _await_metric(lambda: len(gw.live_workers()), 2, 300.0,
                      "the scaled-up worker becoming routable")
        assert "scale0" in gw.live_workers(), gw.live_workers()
        print(f"  scale0 warmed and routable: {gw.live_workers()}")

        # -- Phase 3: partition leg rides the failover contract --------
        # Wave A: scale0's first accepted request arms the 4s
        # blackhole; the gateway's hop stall (1.5s) converts the
        # silence into a retryable failure and every stalled request
        # completes on w0 — no client ever times out, nothing is
        # dropped. Wave B (after the partition window expires) proves
        # scale0 rejoins service on its own bucket.
        n_a = 16
        out2 = loadgen.run_load(gw, frames, n_requests=n_a,
                                concurrency=4, references=refs,
                                timeout=600.0)
        assert out2["completed"] == n_a, \
            f"completed {out2['completed']}/{n_a}"
        assert not out2["dropped"], f"dropped: {out2['dropped']}"
        assert not out2["mismatched"], \
            f"bit-incorrect responses: {out2['mismatched']}"
        retries = sum(gw.metrics.retries.values())
        assert retries >= 1, \
            "partition produced no failover retries"
        print(f"  partition wave: {out2['completed']}/{n_a} bit-exact "
              f"through {retries} failover retr"
              f"{'y' if retries == 1 else 'ies'}")
        time.sleep(4.5)             # let the blackhole window expire
        n_b = 24
        out2b = loadgen.run_load(gw, frames, n_requests=n_b,
                                 concurrency=CONCURRENCY,
                                 references=refs, timeout=600.0)
        assert out2b["completed"] == n_b, \
            f"completed {out2b['completed']}/{n_b}"
        assert not out2b["dropped"], f"dropped: {out2b['dropped']}"
        assert not out2b["mismatched"], \
            f"bit-incorrect responses: {out2b['mismatched']}"
        assert out2b["per_replica"].get("scale0", {}).get(
            "completed", 0) >= 1, \
            (f"scale0 never served post-partition: "
             f"{out2b['per_replica']}")
        print(f"  post-partition wave: {out2b['completed']}/{n_b} "
              f"bit-exact; per-replica = "
              f"{ {k: v['completed'] for k, v in out2b['per_replica'].items()} }")

        # -- Phase 4: load drops -> graceful drain to min_workers ------
        deadline = time.monotonic() + 120.0
        action = None
        while auto.stats()["drains"] == 0:
            if time.monotonic() >= deadline:
                raise AssertionError(
                    f"idle fleet never drained (last action {action}, "
                    f"signals {auto.signals()})")
            action = auto.poll_once()
            time.sleep(0.25)
        victim = next(wid for wid, st in sup.status().items()
                      if st["draining"])
        print(f"  scale-down: draining {victim} "
              f"(target {auto.target_workers})")
        # The supervisor retires the slot on exit 0 — no streak, no
        # breaker, no respawn — and the drained worker removed its own
        # lease on the way out.
        _await_metric(lambda: 1 if victim not in sup.worker_ids()
                      else 0, 1, 120.0, f"{victim}'s slot retiring")
        _await_metric(lambda: 0 if victim in store.read_all() else 1,
                      1, 30.0, f"{victim}'s lease removal")
        _await_metric(lambda: len(gw.live_workers()), 1, 30.0,
                      "routing converging to the survivor")
        assert sup.managed_count() == 1, sup.status()
        survivor_ids = sup.worker_ids()
        print(f"  {victim} drained (exit 0, slot retired, lease "
              f"removed); survivors: {survivor_ids}")

        # Survivors still serve bit-exact with 0 post-warmup compiles.
        out3 = loadgen.run_load(gw, frames, n_requests=20,
                                concurrency=4, references=refs,
                                timeout=300.0)
        assert out3["completed"] == 20 and not out3["dropped"] \
            and not out3["mismatched"], out3
        for wid, lease in sorted(store.read_all().items()):
            compiles = lease.extra.get("post_warmup_compiles")
            assert compiles == 0, \
                f"{wid} reports {compiles} post-warmup compile(s)"
        txt = gw.registry.prometheus_text()
        for needle in ("autoscaler_target_workers 1",
                       "autoscaler_scale_ups 1",
                       "autoscaler_scale_downs 1",
                       "autoscaler_drains 1"):
            assert needle in txt, \
                f"{needle!r} missing from the registry export"
        print(f"  post-drain wave: {out3['completed']}/20 bit-exact; "
              f"0 post-warmup compiles on survivors; autoscaler "
              f"gauges in the export")

        bench_out = os.environ.get("RAFT_BENCH_OUT")
        if bench_out:
            payload = {
                "metric": "autoscale_drill_capacity_convergence",
                "value": float(auto.stats()["drains"]),
                "unit": "graceful_drains",
                "platform": "cpu",
                "smoke_operating_point": True,
                "criterion_note": (
                    "CPU drill topology (small model, 2-bucket load): "
                    "the numbers prove the capacity-convergence "
                    "CONTRACT (scale-up through warming, partition "
                    "failover, graceful drain), not serving "
                    "throughput; on-TPU capture is ROADMAP debt"),
                "drill": {
                    "scale_ups": auto.stats()["scale_ups"],
                    "scale_downs": auto.stats()["scale_downs"],
                    "graceful_drains": auto.stats()["drains"],
                    "failover_retries": retries,
                    "completed": (out1["completed"] + out2["completed"]
                                  + out2b["completed"]
                                  + out3["completed"]),
                    "dropped": 0,
                    "mismatched": 0,
                    "post_warmup_compiles": 0,
                    "brownout_transitions_during_burst": int(
                        w0_lease.extra["brownout_transitions"]),
                    "drained_worker": victim,
                    "survivors": survivor_ids,
                },
            }
            with open(bench_out, "w") as f:
                json.dump(payload, f)
            print(f"  wrote {bench_out}")
    finally:
        auto.close()
        gw.close()
        sup.stop(kill_workers=True)


def _detect_nonloopback_ip():
    """An address of a real (non-loopback) local interface, or None.
    UDP connect() picks the egress interface without sending a byte."""
    import socket as socket_mod
    try:
        s = socket_mod.socket(socket_mod.AF_INET, socket_mod.SOCK_DGRAM)
        try:
            s.connect(("192.0.2.1", 9))     # TEST-NET-1, never routed
            ip = s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return None
    return None if ip.startswith("127.") or ip == "0.0.0.0" else ip


def _run_edge_load(edge_addr, frames, refs, n_requests, concurrency):
    """Drive the HTTP edge with concurrent clients; every request must
    eventually serve bit-exactly. Injected hostile-client behavior
    (slowloris absorption, client abort) is counted and RETRIED — the
    gate is that retries converge, not that the network was polite."""
    from raft_tpu.serving import edge as edge_mod

    res = {"completed": 0, "dropped": [], "mismatched": [],
           "retries": 0, "slowloris_absorbed": 0, "aborts": 0}
    lock = threading.Lock()
    it = iter(range(n_requests))

    def client():
        while True:
            with lock:
                i = next(it, None)
            if i is None:
                return
            fi = i % len(frames)
            im1, im2 = frames[fi]
            for _attempt in range(12):
                try:
                    resp = edge_mod.submit_flow(edge_addr, im1, im2,
                                                timeout=300.0)
                except edge_mod.ClientAbortInjected:
                    with lock:
                        res["aborts"] += 1
                        res["retries"] += 1
                    continue
                except (ConnectionError, OSError):
                    with lock:
                        res["retries"] += 1
                    time.sleep(0.1)
                    continue
                if resp is None:    # this call absorbed the slowloris
                    with lock:
                        res["slowloris_absorbed"] += 1
                        res["retries"] += 1
                    continue
                if resp.status != 200:
                    with lock:
                        res["retries"] += 1
                    time.sleep(0.1)
                    continue
                import numpy as np
                flow = edge_mod.decode_flow(resp)
                with lock:
                    if np.array_equal(flow, refs[fi]):
                        res["completed"] += 1
                    else:
                        res["mismatched"].append(i)
                break
            else:
                with lock:
                    res["dropped"].append(i)

    threads = [threading.Thread(target=client, daemon=True)
               for _ in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600.0)
    return res


def drill_edge(root):
    """The hardened HTTP front door end to end: concurrent HTTP clients
    against edge -> gateway -> 3 worker PROCESSES (one bound 0.0.0.0
    with an advertised non-loopback address) survive a mid-load worker
    SIGKILL, an injected slowloris and an injected client abort with 0
    dropped / 0 bit-incorrect / 0 post-warmup compiles; then SIGTERM
    drains edge -> gateway -> workers in order with /readyz unready
    BEFORE the listener closes."""
    import signal as signal_mod

    from raft_tpu import resilience
    from raft_tpu.serving import edge as edge_mod, loadgen
    from raft_tpu.serving.gateway import (GatewayConfig, ServingGateway,
                                          SocketTransport)
    from raft_tpu.serving.netproto import FileLeaseStore
    from raft_tpu.serving.supervisor import WorkerSpec, WorkerSupervisor
    from raft_tpu.serving.worker import WorkerConfig

    STEP = 0
    lease_dir = os.path.join(root, "leases")
    store = FileLeaseStore(lease_dir)
    ip = _detect_nonloopback_ip()
    if ip:
        print(f"  multi-host leg: w0 binds 0.0.0.0, advertises {ip}")
    else:
        print("  no non-loopback interface found; multi-host leg "
              "degraded to loopback", flush=True)

    def _cfg(i):
        extra = ({"bind_host": "0.0.0.0", "advertise_host": ip}
                 if (i == 0 and ip) else {})
        return WorkerConfig(worker_id=f"w{i}", lease_dir=lease_dir,
                            buckets=BUCKETS, max_batch=4, max_wait_ms=3.0,
                            queue_timeout_ms=60_000, step=STEP,
                            **extra).to_dict()

    specs = [WorkerSpec(f"w{i}", _cfg(i)) for i in range(3)]
    sup = WorkerSupervisor(
        specs, store, stale_after_s=3.0, lease_grace_s=300.0,
        poll_interval_s=0.25, respawn_base_delay_s=0.25,
        respawn_max_delay_s=2.0, min_uptime_s=2.0)
    gw = ServingGateway(store, GatewayConfig(
        queue_timeout_ms=120_000, lease_ttl_s=2.0, poll_interval_s=0.1,
        dispatch_threads=CONCURRENCY, expected_step=STEP))
    sup.attach_registry(gw.registry)
    drain_result = {}
    es = edge_mod.EdgeServer(
        gw,
        edge_mod.EdgeConfig(header_read_timeout_s=2.0,
                            drain_grace_s=1.0),
        drain_workers=lambda: drain_result.update(
            sup.drain_fleet(SocketTransport(), timeout_s=60.0)))
    sup.start_all()
    sup.start()
    gw.start()
    es.start_in_thread()
    es.install_sigterm_handler()
    try:
        _await_metric(lambda: len(gw.live_workers()), 3, 300.0,
                      "routable worker processes")
        print(f"  3 workers routable: {gw.live_workers()}")
        if ip:
            lease0 = store.read_all()["w0"]
            assert tuple(lease0.addr)[0] == ip, lease0.addr
            ping = SocketTransport().request(tuple(lease0.addr),
                                             {"op": "ping"})[0]
            assert ping["status"] == "ok", ping
            print(f"  w0 routable at advertised non-loopback "
                  f"{tuple(lease0.addr)}")
        r = edge_mod.http_request(es.addr, "GET", "/readyz")
        assert r is not None and r.status == 200, r

        predictor = _make_predictor()
        frames = loadgen.make_frames(SHAPES, per_shape=2, seed=29)
        refs, ref_kind = _references(predictor, frames, max_batch=4)

        # -- wave 1: SIGKILL the busiest worker under HTTP load --------
        killed = {}

        def killer():
            _await_metric(lambda: gw.metrics.responses, 5, 120.0,
                          "responses before kill")
            victim = gw.metrics.routed.most_common(1)[0][0]
            pid = store.read_all()[victim].pid
            os.kill(pid, signal_mod.SIGKILL)
            killed["victim"], killed["pid"] = victim, pid
            print(f"  SIGKILLed {victim} (pid {pid}) mid-load",
                  flush=True)

        kt = threading.Thread(target=killer, daemon=True)
        kt.start()
        res = _run_edge_load(es.addr, frames, refs, N_REQUESTS,
                             CONCURRENCY)
        kt.join(timeout=120.0)
        assert "victim" in killed, "kill thread never fired"
        victim = killed["victim"]
        print(f"  {res['completed']}/{N_REQUESTS} HTTP responses "
              f"through the kill; reference = {ref_kind}")
        assert res["completed"] == N_REQUESTS, res
        assert not res["dropped"], f"dropped: {res['dropped']}"
        assert not res["mismatched"], \
            f"bit-incorrect responses: {res['mismatched']}"

        _await_metric(lambda: sup.respawns(victim), 1, 120.0,
                      f"supervised respawn of {victim}")
        _await_metric(lambda: 1 if victim in gw.live_workers() else 0,
                      1, 300.0, f"{victim} rejoining the routable set")
        print(f"  {victim} respawned and rejoined routing")

        # -- wave 2: injected slowloris absorbed by one client ---------
        resilience.set_injector(
            resilience.FaultInjector(edge_slowloris_s=0.05))
        res2 = _run_edge_load(es.addr, frames, refs, 10, 4)
        resilience.set_injector(None)
        assert res2["completed"] == 10 and not res2["dropped"] \
            and not res2["mismatched"], res2
        assert res2["slowloris_absorbed"] >= 1, res2
        assert es.slow_client_drops >= 1, \
            "edge never reaped the injected slowloris"
        print(f"  slowloris injected, reaped by the edge "
              f"(drops={es.slow_client_drops}), victim retried clean")

        # -- wave 3: injected client abort, no poison ------------------
        resilience.set_injector(
            resilience.FaultInjector(edge_client_abort_nth=3))
        res3 = _run_edge_load(es.addr, frames, refs, 10, 4)
        resilience.set_injector(None)
        assert res3["completed"] == 10 and not res3["dropped"] \
            and not res3["mismatched"], res3
        assert res3["aborts"] == 1, res3
        print("  injected client abort retried clean; fleet unpoisoned")

        # 0 post-warmup compiles — cross-process via lease counters.
        for wid, l in sorted(store.read_all().items()):
            compiles = l.extra.get("post_warmup_compiles")
            assert compiles == 0, \
                f"{wid} reports {compiles} post-warmup compile(s)"

        txt = gw.registry.prometheus_text()
        for needle in ("edge_requests", 'edge_responses{status="200"}',
                       'edge_errors{class="slowloris"}', "edge_inflight",
                       "edge_ready"):
            assert needle in txt, f"{needle!r} missing from export"

        # -- SIGTERM: coordinated drain, unready before close ----------
        os.kill(os.getpid(), signal_mod.SIGTERM)
        saw_unready = False
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            try:
                probe = edge_mod.http_request(es.addr, "GET", "/readyz",
                                              timeout=2.0)
            except (ConnectionError, OSError):
                break               # listener already closed
            if probe is not None and probe.status == 503:
                saw_unready = True
                break
            time.sleep(0.02)
        assert saw_unready, \
            "/readyz never went 503 while the listener was still open"
        deadline = time.monotonic() + 180.0
        while ("workers_drained" not in es.shutdown_events
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert es.shutdown_events == [
            "unready", "listener_closed", "edge_drained",
            "gateway_closed", "workers_drained"], es.shutdown_events
        assert drain_result and set(drain_result.values()) <= \
            {"drained", "not-running"}, drain_result
        print(f"  SIGTERM drained edge->gateway->workers in order; "
              f"workers: {drain_result}")
    finally:
        resilience.set_injector(None)
        if not es._closed:
            es.shutdown_sync()
        gw.close()
        sup.stop(kill_workers=True)


def drill_reliability(root):
    """End-to-end request reliability: idempotent dispatch replays a
    reply lost after acceptance (exactly-once compute effect, proven
    by lease-published audit counters), injected duplicate delivery
    collapses in the worker's dedup cache, tail-latency hedging
    rescues a partition-stalled request under budget, and an
    SDC-failed worker is quarantined and recycled without crash
    accounting. Gate: 0 dropped, 0 bit-incorrect, 0 post-warmup
    compiles."""
    import json
    import signal as signal_mod

    import numpy as np

    from raft_tpu.serving import loadgen
    from raft_tpu.serving.fleet import BucketRouter
    from raft_tpu.serving.gateway import GatewayConfig, ServingGateway
    from raft_tpu.serving.health import is_routable
    from raft_tpu.serving.netproto import FileLeaseStore
    from raft_tpu.serving.supervisor import WorkerSpec, WorkerSupervisor
    from raft_tpu.serving.worker import WorkerConfig

    STEP = 0
    predictor = _make_predictor()
    frames = loadgen.make_frames(SHAPES, per_shape=2, seed=37)
    refs, ref_kind = _references(predictor, frames, max_batch=4)
    print(f"  reference = {ref_kind}")

    # ---- Stage A: reply loss + duplicate delivery, ONE owner --------
    # A single-worker fleet makes the retry-after-send contract
    # unambiguous: a reply dropped post-acceptance leaves the gateway
    # no other owner, so completing the request REQUIRES the same-key
    # chain rewalk back to the same worker and a dedup-cache replay —
    # provable cross-process via the lease-published audit counters.
    lease_a = os.path.join(root, "leases_a")
    store_a = FileLeaseStore(lease_a)
    # spawn_worker treats env as a full REPLACEMENT — merge over the
    # parent environment (JAX_PLATFORMS et al).
    env_a = dict(os.environ,
                 RAFT_FAULT_WORKER_SOCKET_DROP="2",
                 RAFT_FAULT_WORKER_DUP_DELIVERY_NTH="5")
    sup_a = WorkerSupervisor(
        [WorkerSpec("solo0", WorkerConfig(
            worker_id="solo0", lease_dir=lease_a, buckets=BUCKETS,
            max_batch=4, max_wait_ms=3.0, queue_timeout_ms=60_000,
            step=STEP).to_dict(), env=env_a)],
        store_a, stale_after_s=3.0, lease_grace_s=300.0,
        poll_interval_s=0.25, respawn_base_delay_s=0.25,
        respawn_max_delay_s=2.0, min_uptime_s=2.0)
    gw_a = ServingGateway(store_a, GatewayConfig(
        queue_timeout_ms=120_000, lease_ttl_s=2.0, poll_interval_s=0.1,
        dispatch_threads=4, expected_step=STEP))
    sup_a.start_all()
    sup_a.start()
    gw_a.start()
    n_a = 24
    try:
        _await_metric(lambda: len(gw_a.live_workers()), 1, 300.0,
                      "the solo worker becoming routable")
        res_a = loadgen.run_load(gw_a, frames, n_requests=n_a,
                                 concurrency=4, references=refs,
                                 timeout=600.0)
        assert res_a["completed"] == n_a, \
            f"completed {res_a['completed']}/{n_a}"
        assert not res_a["dropped"], f"dropped: {res_a['dropped']}"
        assert not res_a["mismatched"], \
            f"bit-incorrect responses: {res_a['mismatched']}"
        # Two dropped replies, one owner: each MUST have completed via
        # a chain rewalk (retry-after-send) — the PR-18 refusal is gone.
        rewalks_a = gw_a.metrics.chain_rewalks
        retries_a = sum(gw_a.metrics.retries.values())
        assert rewalks_a >= 2, \
            f"expected >=2 chain rewalks for 2 dropped replies, " \
            f"got {rewalks_a}"
        assert retries_a >= 2, \
            f"expected >=2 same-key retries, got {retries_a}"

        # Cross-process audit via the worker's own lease heartbeat
        # (the audit counters ride the lease's ``dedup`` extra).
        def _solo_computes():
            lease = store_a.read_all().get("solo0")
            if lease is None:
                return 0
            return int(lease.extra.get("dedup", {}).get("computes", 0))

        _await_metric(_solo_computes, n_a, 30.0,
                      "solo0's lease publishing its compute count")
        lease = store_a.read_all()["solo0"]
        dd = lease.extra["dedup"]
        replays_a = int(dd["replays"])
        hits_inflight_a = int(dd["hits_inflight"])
        dups_a = int(dd["dup_deliveries"])
        computes_a = int(dd["computes"])
        # 2 lost-reply retries + 1 injected duplicate, all answered
        # from the idempotency cache (replay or in-flight attach)...
        assert replays_a + hits_inflight_a >= 3, lease.extra
        assert dups_a == 1, lease.extra
        # ...and the EXACTLY-ONCE EFFECT: computes == unique requests
        # despite deliveries > requests.
        assert computes_a == n_a, \
            f"exactly-once violated: {computes_a} computes for " \
            f"{n_a} requests ({lease.extra})"
        assert lease.extra.get("post_warmup_compiles") == 0, lease.extra
        print(f"  stage A: {n_a}/{n_a} bit-exact through 2 dropped "
              f"replies + 1 duplicate delivery; rewalks={rewalks_a}, "
              f"replays={replays_a}, inflight-hits={hits_inflight_a}, "
              f"computes={computes_a} (exactly-once)")
    finally:
        gw_a.close()
        sup_a.stop(kill_workers=True)

    # ---- Stage B: SIGKILL + hedged stall + SDC quarantine -----------
    lease_b = os.path.join(root, "leases_b")
    store_b = FileLeaseStore(lease_b)

    def _cfg_b(wid, **kw):
        return WorkerConfig(
            worker_id=wid, lease_dir=lease_b, buckets=BUCKETS,
            max_batch=4, max_wait_ms=3.0, queue_timeout_ms=60_000,
            step=STEP, **kw).to_dict()

    base_ids = ["w0", "w1", "w2"]
    sup = WorkerSupervisor(
        [WorkerSpec(w, _cfg_b(w)) for w in base_ids], store_b,
        stale_after_s=3.0, lease_grace_s=300.0, poll_interval_s=0.25,
        respawn_base_delay_s=0.25, respawn_max_delay_s=2.0,
        min_uptime_s=2.0)
    hedge_fraction = 0.5
    gw = ServingGateway(store_b, GatewayConfig(
        queue_timeout_ms=120_000, lease_ttl_s=2.0, poll_interval_s=0.1,
        dispatch_threads=CONCURRENCY, expected_step=STEP,
        hedge_quantile=0.9, hedge_min_ms=50.0, hedge_min_samples=6,
        hedge_budget_fraction=hedge_fraction))
    sup.attach_registry(gw.registry)
    sup.start_all()
    sup.start()
    gw.start()
    try:
        _await_metric(lambda: len(gw.live_workers()), 3, 300.0,
                      "3 workers routable")

        killed = {}

        def killer():
            _await_metric(lambda: gw.metrics.responses, 5, 120.0,
                          "responses before kill")
            victim = gw.metrics.routed.most_common(1)[0][0]
            pid = store_b.read_all()[victim].pid
            os.kill(pid, signal_mod.SIGKILL)
            killed["victim"], killed["pid"] = victim, pid
            print(f"  SIGKILLed {victim} (pid {pid}) mid-load",
                  flush=True)

        kt = threading.Thread(target=killer, daemon=True)
        kt.start()
        n_warm = 40
        res1 = loadgen.run_load(gw, frames, n_requests=n_warm,
                                concurrency=CONCURRENCY,
                                references=refs, timeout=600.0)
        kt.join(timeout=120.0)
        assert "victim" in killed, "kill thread never fired"
        victim = killed["victim"]
        assert res1["completed"] == n_warm, \
            f"completed {res1['completed']}/{n_warm}"
        assert not res1["dropped"], f"dropped: {res1['dropped']}"
        assert not res1["mismatched"], \
            f"bit-incorrect responses: {res1['mismatched']}"
        retries_b = sum(gw.metrics.retries.values())
        assert retries_b >= 1, \
            "SIGKILL produced no post-acceptance retries"
        print(f"  stage B warm wave: {n_warm}/{n_warm} bit-exact "
              f"through the {victim} SIGKILL ({retries_b} same-key "
              f"retries)")

        # The stall worker must OWN a bucket or the partition never
        # arms. Rendezvous scores are per-(key, id), so an id that
        # tops a key in the superset tops it in every live subset.
        stall_wid = stall_key = None
        for i in range(1000):
            cand = f"stall{i}"
            r = BucketRouter(base_ids + [cand])
            for k in ("40x64", "56x80"):
                if r.owners_for_key(k)[0] == cand:
                    stall_wid, stall_key = cand, k
                    break
            if stall_wid:
                break
        assert stall_wid, "no rendezvous-winning stall worker id found"
        sup.add_worker(WorkerSpec(
            stall_wid, _cfg_b(stall_wid),
            env=dict(os.environ, RAFT_FAULT_WORKER_PARTITION_S="8.0")))

        _await_metric(lambda: sup.respawns(victim), 1, 120.0,
                      f"supervised respawn of {victim}")
        _await_metric(lambda: 1 if victim in gw.live_workers() else 0,
                      1, 300.0, f"{victim} rejoining the routable set")
        _await_metric(
            lambda: 1 if stall_wid in gw.live_workers() else 0,
            1, 300.0, f"{stall_wid} becoming routable")

        # A frame whose padded bucket the stall worker owns: its first
        # delivery arms the 8s blackhole; the gateway's hedge (p90 +
        # 50ms floor, budget permitting) must rescue it on the next
        # owner under the SAME idempotency key.
        key_of = {(36, 60): "40x64", (33, 57): "40x64",
                  (52, 76): "56x80"}
        si = next(i for i, (a, _b) in enumerate(frames)
                  if key_of[a.shape[:2]] == stall_key)
        im1, im2 = frames[si]
        h0, hw0 = gw.metrics.hedges, gw.metrics.hedge_wins
        f1 = gw.submit(im1, im2)
        flow1 = f1.result(120.0)
        assert np.array_equal(flow1, refs[si]), \
            "hedged request not bit-exact"
        assert f1.replica_id != stall_wid, \
            f"stalled worker {stall_wid} somehow answered first"
        for _ in range(2):
            fx = gw.submit(im1, im2)
            assert np.array_equal(fx.result(120.0), refs[si]), \
                "request during the stall window not bit-exact"
        hedges_fired = gw.metrics.hedges - h0
        hedge_wins = gw.metrics.hedge_wins - hw0
        assert hedges_fired >= 1, "the stall fired no hedge"
        assert hedge_wins >= 1, \
            f"no hedge win against the stalled primary " \
            f"(fired {hedges_fired})"
        print(f"  hedge vs stall: {hedges_fired} fired, {hedge_wins} "
              f"won; winner={f1.replica_id} (stalled={stall_wid})")

        # The SDC worker must NOT steal a bucket from the stall worker
        # (the post-stall wave asserts the stall worker serves again).
        sdc_wid = None
        for i in range(1000):
            cand = f"sdc{i}"
            r = BucketRouter(base_ids + [stall_wid, cand])
            if all(r.owners_for_key(k)[0] != cand
                   for k in ("40x64", "56x80")):
                sdc_wid = cand
                break
        assert sdc_wid, "no non-owning sdc worker id found"
        # Long self-check interval: the recycled replacement gets a
        # routable window (the spec's env — injector included — rides
        # every respawn) before its own sentinel trips again.
        sup.add_worker(WorkerSpec(
            sdc_wid, _cfg_b(sdc_wid, self_check_interval_s=8.0),
            env=dict(os.environ, RAFT_FAULT_WORKER_SDC_NTH="1")))

        time.sleep(8.5)             # let the blackhole window expire
        n_post = 16
        res2 = loadgen.run_load(gw, frames, n_requests=n_post,
                                concurrency=4, references=refs,
                                timeout=600.0)
        assert res2["completed"] == n_post and not res2["dropped"] \
            and not res2["mismatched"], res2
        assert res2["per_replica"].get(stall_wid, {}).get(
            "completed", 0) >= 1, \
            (f"{stall_wid} never served post-partition: "
             f"{res2['per_replica']}")
        print(f"  post-stall wave: {res2['completed']}/{n_post} "
              f"bit-exact; {stall_wid} back in rotation")

        # SDC sentinel: the worker joins routable, its first periodic
        # self-check is corrupted -> QUARANTINED -> the supervisor
        # recycles it WITHOUT crash accounting and the replacement
        # rejoins routable.
        _await_metric(lambda: 1 if sdc_wid in gw.live_workers() else 0,
                      1, 300.0, f"{sdc_wid} warmed and routable")
        first_pid = store_b.read_all()[sdc_wid].pid
        _await_metric(
            lambda: sup.status()[sdc_wid]["quarantine_recycles"],
            1, 180.0, f"the quarantine recycle of {sdc_wid}")
        st = sup.status()[sdc_wid]
        assert st["crash_streak"] == 0, \
            f"quarantine counted as a crash: {st}"
        assert st["breaker"] == "closed", st
        quarantine_recycles = int(st["quarantine_recycles"])

        def _sdc_rejoined():
            lease = store_b.read_all().get(sdc_wid)
            if lease is None or lease.pid == first_pid:
                return 0
            return 1 if sdc_wid in gw.live_workers() else 0

        _await_metric(_sdc_rejoined, 1, 300.0,
                      f"{sdc_wid}'s replacement rejoining routable")
        sdc_lease = store_b.read_all()[sdc_wid]
        assert is_routable(sdc_lease.state), sdc_lease.state
        assert sdc_lease.extra.get("post_warmup_compiles") == 0, \
            sdc_lease.extra
        print(f"  SDC: {sdc_wid} quarantined and recycled "
              f"(recycles={quarantine_recycles}, crash_streak=0); "
              f"replacement pid {sdc_lease.pid} routable")

        # Hedges stay budget-capped fleet-wide, and the reliability
        # gauges ride the Prometheus export.
        total_requests = gw.metrics.requests
        assert gw.metrics.hedges <= \
            hedge_fraction * total_requests + 4.0, \
            (f"hedges {gw.metrics.hedges} exceed budget "
             f"{hedge_fraction} of {total_requests} requests")
        txt = gw.registry.prometheus_text()
        for needle in (
                f'gateway_worker_quarantine_recycles{{worker="{sdc_wid}"}}',
                "gateway_hedges", "gateway_hedge_wins",
                "gateway_chain_rewalks"):
            assert needle in txt, f"{needle!r} missing from export"

        # Zero post-warmup compiles on every lease-holder.
        for wid, l in sorted(store_b.read_all().items()):
            compiles = l.extra.get("post_warmup_compiles", 0)
            assert compiles == 0, \
                f"{wid} reports {compiles} post-warmup compile(s)"
        print("  0 post-warmup compiles fleet-wide; reliability "
              "gauges in the registry export")

        bench_out = os.environ.get("RAFT_BENCH_OUT")
        if bench_out:
            payload = {
                "metric": "reliability_drill_exactly_once_effect",
                "value": float(replays_a + hits_inflight_a),
                "unit": "deduped_duplicate_replies",
                "platform": "cpu",
                "smoke_operating_point": True,
                "criterion_note": (
                    "CPU drill topology (small model, 2-bucket load): "
                    "the numbers prove the request-reliability "
                    "CONTRACT (idempotent replay after reply loss, "
                    "budget-capped hedging, SDC quarantine recycle), "
                    "not serving throughput; on-TPU capture is "
                    "ROADMAP debt"),
                "drill": {
                    "completed": (res_a["completed"]
                                  + res1["completed"]
                                  + res2["completed"] + 3),
                    "dropped": 0,
                    "mismatched": 0,
                    "post_warmup_compiles": 0,
                    "dedup_replays": replays_a,
                    "dedup_hits_inflight": hits_inflight_a,
                    "dup_deliveries": dups_a,
                    "worker_computes": computes_a,
                    "chain_rewalks": rewalks_a,
                    "failover_retries": retries_b,
                    "hedges": int(gw.metrics.hedges),
                    "hedge_wins": int(gw.metrics.hedge_wins),
                    "quarantine_recycles": quarantine_recycles,
                },
            }
            with open(bench_out, "w") as f:
                json.dump(payload, f)
            print(f"  wrote {bench_out}")
    finally:
        gw.close()
        sup.stop(kill_workers=True)


DRILLS = [
    drill_smoke,
    drill_breaker_isolation,
    drill_reload_under_load,
    drill_fleet,
    drill_streaming,
    drill_brownout,
    drill_pallas_kernels,
    drill_highres,
    drill_wire,
    drill_trace,
    drill_contbatch,
    drill_gateway,
    drill_autoscale,
    drill_edge,
    drill_reliability,
]


def _drill_name(fn) -> str:
    return fn.__name__[len("drill_"):].replace("_", "-")


def main(argv=None) -> int:
    from raft_tpu.resilience import set_injector

    by_name = {_drill_name(fn): fn for fn in DRILLS}
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--drill", default="all",
                    choices=["all", *by_name],
                    help="run one drill (default: all)")
    ap.add_argument("--list", action="store_true",
                    help="print available drills and exit")
    args = ap.parse_args(argv)
    if args.drill in ("all", "highres"):
        # The highres drill shards one request's rows over a 1x4 spatial
        # mesh; on this CPU host the devices come from the forced host-
        # platform count. Must be set before jax initializes its backend
        # (first jax.devices() call inside a drill) — the other drills'
        # bit-exactness checks adapt to the topology via _references.
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    if args.list:
        for fn in DRILLS:
            doc = (fn.__doc__ or "").strip().split("\n")[0]
            print(f"{_drill_name(fn):24s} {doc}")
        return 0
    selected = DRILLS if args.drill == "all" else [by_name[args.drill]]

    failures = 0
    for drill in selected:
        name = drill.__name__
        set_injector(None)
        with tempfile.TemporaryDirectory(prefix=f"{name}_") as root:
            print(f"=== {name} ===", flush=True)
            try:
                drill(root)
            except Exception:
                failures += 1
                print(f"FAIL {name}", flush=True)
                traceback.print_exc()
            else:
                print(f"PASS {name}", flush=True)
            finally:
                set_injector(None)
    print(f"\n{len(selected) - failures}/{len(selected)} drills passed",
          flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
