#!/usr/bin/env python
"""CPU smoke drill for the serving engine (CI gate, runs in minutes).

Warms two buckets, fires 50 concurrent requests through
:class:`raft_tpu.serving.engine.ServingEngine`, and exits nonzero on
ANY dropped or incorrect response. Correctness is bit-exact: every
served flow must equal the direct ``FlowPredictor`` output for the same
pair — on this script's single-process default topology the batch-1
``__call__`` path and the batched serve path are bit-identical (the
acceptance criterion's wording); under a forced multi-device topology
(``XLA_FLAGS=--xla_force_host_platform_device_count=N``) the check
automatically uses the same-executable batched reference instead, which
is exact on any topology (see loadgen docstring).

Also asserts the warmup contract — after the two buckets pre-compile,
the 50 requests trigger ZERO fresh XLA compiles — and prints a one-line
summary plus the engine's metrics report.

Usage::

    JAX_PLATFORMS=cpu python scripts/serve_drill.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

N_REQUESTS = 50
CONCURRENCY = 8
# Two raw shapes per bucket: (36,60) and (33,57) share the (40,64)
# bucket; (52,76) pads to (56,80) — two buckets total, three raw shapes.
SHAPES = [(36, 60), (33, 57), (52, 76)]
BUCKETS = ((36, 60), (52, 76))


def main() -> int:
    import jax

    from raft_tpu.evaluate import load_predictor
    from raft_tpu.serving import (CompileWatch, ServingConfig,
                                  ServingEngine, loadgen)

    predictor = load_predictor("random", small=True, iters=2)
    frames = loadgen.make_frames(SHAPES, per_shape=2, seed=11)
    if jax.device_count() == 1:
        refs = loadgen.reference_flows(predictor, frames)
        ref_kind = "direct __call__ (batch-1, bit-exact single-device)"
    else:
        refs = loadgen.batched_reference_flows(predictor, frames,
                                               max_batch=4)
        ref_kind = (f"same-executable batched ({jax.device_count()} "
                    "devices: cross-executable float order differs)")

    engine = ServingEngine(predictor, ServingConfig(
        max_batch=4, max_wait_ms=3.0, buckets=BUCKETS))
    warm = engine.warmup()
    engine.start(warmup=False)
    try:
        with CompileWatch() as watch:
            res = loadgen.run_load(engine, frames, n_requests=N_REQUESTS,
                                   concurrency=CONCURRENCY,
                                   references=refs)
    finally:
        engine.close()

    failures = []
    if res["completed"] != N_REQUESTS:
        failures.append(f"completed {res['completed']}/{N_REQUESTS}")
    if res["dropped"]:
        failures.append(f"dropped requests: {res['dropped']}")
    if res["mismatched"]:
        failures.append(f"incorrect responses: {res['mismatched']}")
    if len(warm) != len(BUCKETS):
        failures.append(f"warmup covered {len(warm)} of "
                        f"{len(BUCKETS)} buckets")
    if watch.compiles:
        failures.append(f"{watch.compiles} fresh XLA compile(s) after "
                        "warmup (warmup contract broken)")

    print(f"serve_drill: {res['completed']}/{N_REQUESTS} responses, "
          f"{res['throughput_rps']:.1f} req/s at concurrency "
          f"{CONCURRENCY}; reference = {ref_kind}")
    warm_desc = ", ".join(f"{k}: {int(v['compiles'])}"
                          for k, v in warm.items())
    print(f"warmup: {{bucket: compiles}} = {{{warm_desc}}}")
    print("metrics:", engine.metrics.report())
    print("host stages:", engine.stages.report())
    if failures:
        for f in failures:
            print("FAIL:", f)
        return 1
    print("PASS: all responses bit-exact, no post-warmup compiles")
    return 0


if __name__ == "__main__":
    sys.exit(main())
