#!/usr/bin/env python
"""Batch-knee sweep for the round-4 headline engine (run alone on TPU).

The bench headline batch (24) was tuned in round 2 for the
*materialized* engine, whose f32 volume pyramid for 24 pairs fills
~6 GB of HBM. The banded on-demand engine stores no volume
(volume_memory: 0.69 vs 1.07 GB at b4), so its throughput knee may sit
at a larger batch. Sweeps Sintel-resolution test_mode forward over
batch sizes on both engines and prints one JSON line; feeds the
bench.py BATCH decision (recorded in BASELINE.md).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".jax_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")

import jax
import jax.numpy as jnp

_res = os.environ.get("RAFT_KNEE_RES", "440,1024").split(",")
if len(_res) != 2:
    raise SystemExit(f"RAFT_KNEE_RES must be 'H,W', got "
                     f"{os.environ['RAFT_KNEE_RES']!r}")
H, W = int(_res[0]), int(_res[1])
ITERS = int(os.environ.get("RAFT_KNEE_ITERS", "12"))
# The round-6 fused GRU kernel changes the per-iteration cost, so the
# knee may move; RAFT_KNEE_GRU pins RAFT_GRU_PALLAS for the whole sweep
# and the payload records which arm produced the numbers.
if os.environ.get("RAFT_KNEE_GRU"):
    os.environ["RAFT_GRU_PALLAS"] = os.environ["RAFT_KNEE_GRU"]
WARMUP, REPS = 2, 6
BATCHES = tuple(int(b) for b in
                os.environ.get("RAFT_KNEE_BATCHES", "24,32,48,64").split(","))


def main():
    from raft_tpu.config import RAFTConfig
    from raft_tpu.models.raft import RAFT
    from raft_tpu.ops.corr_pallas import run_with_band_retry

    rng = jax.random.PRNGKey(0)
    img1 = jax.random.uniform(rng, (1, H, W, 3), jnp.float32) * 255.0
    base = RAFT(RAFTConfig(iters=ITERS, mixed_precision=True))
    variables = base.init({"params": rng, "dropout": rng}, img1, img1,
                          iters=1)
    out = {"resolution": [H, W], "iters": ITERS, "reps": REPS,
           "gru": os.environ.get("RAFT_GRU_PALLAS") or "auto"}

    for name, alt in (("alternate", True), ("all_pairs", False)):
        model = RAFT(RAFTConfig(iters=ITERS, mixed_precision=True,
                                alternate_corr=alt))

        for batch in BATCHES:
            def arm(batch=batch, model=model, name=name):
                # jit constructed per attempt (not hoisted): after a
                # *runtime* failure the band-retry ladder changes
                # RAFT_CORR_BAND, and a hoisted jit would replay the
                # cached failing executable on every rung instead of
                # re-tracing under the new env (ADVICE r4 low-1;
                # bench.py's alternate_arm does the same).
                fwd = jax.jit(lambda a, b, m=model: (
                    lambda f: (f, jnp.sum(f)))(m.apply(variables, a, b,
                                                       test_mode=True)[1]))
                img = jnp.broadcast_to(img1, (batch, H, W, 3))
                for _ in range(WARMUP):
                    float(fwd(img, img)[1])
                t0 = time.perf_counter()
                for _ in range(REPS):
                    o = fwd(img, img)
                float(o[1])
                rate = REPS * batch / (time.perf_counter() - t0)
                out[f"{name}_b{batch}_pairs_per_sec"] = round(rate, 2)

            if alt:
                if not run_with_band_retry(arm, out, f"{name}_b{batch}"):
                    break               # OOM/compile wall: stop climbing
            else:
                try:
                    arm()
                except Exception as e:
                    out[f"{name}_b{batch}_error"] = \
                        f"{type(e).__name__}: {e}"
                    break
    print(json.dumps(out))


if __name__ == "__main__":
    main()
