"""Input-pipeline steady-state throughput bench (VERDICT r4 #3).

Answers the question every train bench to date has skipped: can the
host-side loader (read + decode + augment + batch-stack,
``raft_tpu/data/datasets.py::DataLoader``) actually feed the measured
device train rate (49.3 samples/s at chairs b8, ``TPU_EXTRAS.json``
raft_train alt arms)?

Method: a synthetic-but-real-shaped FlyingChairs stand-in — real .ppm /
.flo files on disk at chairs native resolution (384x512), read through
the real ``frame_utils`` decoders and the real ``FlowAugmentor`` with
the chairs stage's aug params (crop 368x496, the raft_train operating
shape) — so the measured rate includes file IO, decode, photometric +
spatial aug, and batch stacking. No GPU/TPU involvement: this is pure
host work, runnable anywhere.

Output: one JSON line with samples/s per (loader, num_workers) arm and
the device-rate comparison. Writes ``LOADER_BENCH.json``.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

# chairs b8 on-demand-engine device rate, TPU_EXTRAS raft_train alt arms
DEVICE_RATE = 49.3
N_FILES = 48            # distinct samples on disk (loops as needed)
H, W = 384, 512         # chairs native resolution
CROP = (368, 496)       # chairs training crop (train_standard.sh stage 1)
BATCH = 8
MEASURE_BATCHES = 40    # per arm, after warmup
WARMUP_BATCHES = 6


def _write_ppm(path: str, img: np.ndarray) -> None:
    with open(path, "wb") as f:
        f.write(b"P6\n%d %d\n255\n" % (img.shape[1], img.shape[0]))
        f.write(img.astype(np.uint8).tobytes())


def make_fixture(root: str) -> None:
    from raft_tpu.data import frame_utils
    rng = np.random.default_rng(0)
    for i in range(N_FILES):
        # low-frequency patterns (compressible like real frames, and the
        # augmentor's float math sees realistic value ranges)
        low = rng.uniform(0, 255, (H // 8, W // 8, 3))
        img = np.kron(low, np.ones((8, 8, 1)))[:H, :W]
        _write_ppm(os.path.join(root, f"{i:05d}_img1.ppm"), img)
        _write_ppm(os.path.join(root, f"{i:05d}_img2.ppm"),
                   np.roll(img, (3, 5), axis=(0, 1)))
        flow = rng.uniform(-10, 10, (H, W, 2)).astype(np.float32)
        frame_utils.write_flo(os.path.join(root, f"{i:05d}_flow.flo"),
                              flow)


def make_dataset(root: str):
    from raft_tpu.data.datasets import FlowDataset
    ds = FlowDataset(aug_params=dict(
        crop_size=CROP, min_scale=-0.1, max_scale=1.0, do_flip=True),
        seed=0)
    for i in range(N_FILES):
        ds.image_list.append((os.path.join(root, f"{i:05d}_img1.ppm"),
                              os.path.join(root, f"{i:05d}_img2.ppm")))
        ds.flow_list.append(os.path.join(root, f"{i:05d}_flow.flo"))
    return ds


def run_arm(loader) -> float:
    """Steady-state samples/s over MEASURE_BATCHES after warmup,
    re-iterating (fresh epochs) as needed."""
    it = iter(loader)
    n = 0
    t0 = None
    while n < WARMUP_BATCHES + MEASURE_BATCHES:
        try:
            batch = next(it)
        except StopIteration:
            it = iter(loader)
            continue
        assert batch["image1"].shape == (BATCH, *CROP, 3)
        n += 1
        if n == WARMUP_BATCHES:
            t0 = time.perf_counter()
    return MEASURE_BATCHES * BATCH / (time.perf_counter() - t0)


def main():
    from raft_tpu import native
    from raft_tpu.data.datasets import DataLoader

    root = tempfile.mkdtemp(prefix="loader_bench_")
    out = {"resolution": [H, W], "crop": list(CROP), "batch": BATCH,
           "device_rate_samples_per_sec": DEVICE_RATE,
           "native_augment": bool(native.available()),
           "cpu_count": os.cpu_count()}
    try:
        make_fixture(root)
        # replicate so one epoch covers warmup+measurement — re-iterating
        # mid-arm would re-fork the process pool and charge pool startup
        # to the steady-state number
        ds = 20 * make_dataset(root)

        # single-sample cost breakdown (sequential, no loader overhead)
        t0 = time.perf_counter()
        for i in range(32):
            ds[i % N_FILES]
        out["sequential_samples_per_sec"] = round(
            32 / (time.perf_counter() - t0), 2)

        for workers in (1, 4, 8, 16):
            loader = DataLoader(ds, batch_size=BATCH, shuffle=True,
                                num_workers=workers, prefetch=4)
            rate = run_arm(loader)
            out[f"thread_w{workers}_samples_per_sec"] = round(rate, 2)

        try:
            from raft_tpu.data.datasets import ProcessDataLoader
        except ImportError:
            ProcessDataLoader = None
        if ProcessDataLoader is not None:
            arm_counts = (4, 8, 16) if (os.cpu_count() or 1) >= 4 else (2,)
            for workers in arm_counts:
                loader = ProcessDataLoader(ds, batch_size=BATCH,
                                           shuffle=True,
                                           num_workers=workers,
                                           prefetch=4)
                rate = run_arm(loader)
                out[f"process_w{workers}_samples_per_sec"] = round(rate, 2)

        # only actual loader arms — the sequential probe is a cost
        # breakdown, not a configuration training can run
        best = max(v for k, v in out.items()
                   if k.startswith(("thread_", "process_")))
        out["best_samples_per_sec"] = best
        out["feeds_device"] = bool(best >= DEVICE_RATE)
    finally:
        shutil.rmtree(root, ignore_errors=True)

    print(json.dumps(out))
    with open("LOADER_BENCH.json", "w") as f:
        json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
