#!/usr/bin/env python
"""Validate the committed BENCH_*.json artifacts' honesty contract.

bench.py's discipline is that context travels WITH the artifact: one
JSON object per capture, values never faked, and any number measured at
a smoke operating point says so in the payload instead of impersonating
an on-chip capture. This checker enforces the shape that every artifact
committed so far actually has, so a future round can't silently commit
a payload that drops the honesty keys:

* **Wrapper records** (``BENCH_r01..r05`` style, written by the round
  driver): ``{"cmd", "rc", "parsed", ...}``. ``parsed`` is either the
  bench payload (validated like any payload) or ``null`` — allowed only
  with a nonzero ``rc``, i.e. an honest record of a failed/timed-out
  run, never a silently empty success.
* **Payloads** (direct ``_emit`` output, or a wrapper's ``parsed``):
  - error records carry ``metric`` + non-empty ``error`` and a null
    ``value`` — a failure is recorded, not dressed up as a number;
  - measurements carry ``metric``/``unit`` strings, a numeric
    ``value``, and a ``platform`` string;
  - measurements taken OFF-TPU (the smoke hosts) must carry at least
    one smoke-honesty key — ``smoke_operating_point`` or
    ``criterion_note`` — naming what the number does and does not
    claim. TPU captures need no disclaimer; they ARE the claim.
  - an optional ``trace_artifact`` key (written by ``bench.py serving
    --trace``) must be a path to an existing Chrome trace-event JSON
    file (top-level object with a ``traceEvents`` list) — a claimed
    trace that doesn't exist or doesn't load in Perfetto is a
    violation, same spirit as a faked value.

Run directly (``python scripts/check_bench_schema.py``, nonzero exit on
any violation) or through the fast test ``tests/test_bench_schema.py``.
"""

from __future__ import annotations

import glob
import json
import numbers
import os
import sys
from typing import List

SMOKE_HONESTY_KEYS = ("smoke_operating_point", "criterion_note")

# A/B artifacts: a ratio/overhead only means something if BOTH arms
# were measured in the same run. A payload carrying one of these
# metrics (without an error) must ship both arms' numbers in
# ``per_arm``. contbatch is the round-9 speedup claim; gateway is the
# multi-process tier's hop-overhead claim (in-process fleet submit vs
# the same load through the socket gateway); step is the round-10
# one-launch refine-iteration claim (fused motion→GRU kernel vs the
# chained two-launch path — the xla arm is informative, not required).
CONTBATCH_METRIC = "contbatch_vs_bucketed_mixed_iters_throughput_speedup"
CONTBATCH_ARMS = ("continuous", "bucketed")
GATEWAY_METRIC = "gateway_vs_inprocess_p50_latency_overhead_ms"
GATEWAY_ARMS = ("in_process", "gateway")
STEP_METRIC = "fused_step_vs_chained_pairs_per_sec_speedup"
STEP_ARMS = ("fused", "chained")
# edge is the HTTP front door's toll claim: the same load served
# in-process vs through edge -> gateway -> worker over real HTTP.
EDGE_METRIC = "edge_vs_inprocess_p50_latency_overhead_ms"
EDGE_ARMS = ("in_process", "edge")
AB_METRICS = {
    CONTBATCH_METRIC: ("contbatch", CONTBATCH_ARMS),
    GATEWAY_METRIC: ("gateway", GATEWAY_ARMS),
    STEP_METRIC: ("step", STEP_ARMS),
    EDGE_METRIC: ("edge", EDGE_ARMS),
}

# The autoscale drill's artifact is a contract record, not a speedup
# claim: its honesty is the drill's counters travelling with it. A
# payload carrying this metric must ship the counters that make the
# "converged with zero loss" claim auditable.
AUTOSCALE_METRIC = "autoscale_drill_capacity_convergence"
AUTOSCALE_COUNTERS = ("scale_ups", "graceful_drains", "failover_retries",
                      "completed", "dropped", "mismatched",
                      "post_warmup_compiles")

# The reliability drill's artifact is likewise a contract record: the
# exactly-once claim (computes == unique requests despite duplicate
# deliveries and lost replies) plus the hedge/quarantine lifecycle are
# only auditable through the counters riding with the number.
RELIABILITY_METRIC = "reliability_drill_exactly_once_effect"
RELIABILITY_COUNTERS = ("completed", "dropped", "mismatched",
                        "post_warmup_compiles", "dedup_replays",
                        "dedup_hits_inflight", "dup_deliveries",
                        "worker_computes", "chain_rewalks",
                        "failover_retries", "hedges", "hedge_wins",
                        "quarantine_recycles")


def _check_trace_artifact(path) -> List[str]:
    """Validate a payload's optional ``trace_artifact`` reference: the
    path must exist and parse as Chrome trace-event JSON (an object
    carrying a ``traceEvents`` list)."""
    if not isinstance(path, str) or not path:
        return ["'trace_artifact' must be a non-empty path string"]
    if not os.path.isfile(path):
        return [f"'trace_artifact' path does not exist: {path!r}"]
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"'trace_artifact' is not readable JSON ({e})"]
    if not isinstance(doc, dict) or not isinstance(
            doc.get("traceEvents"), list):
        return ["'trace_artifact' is not Chrome trace-event JSON "
                "(needs a 'traceEvents' list)"]
    return []


def check_payload(name: str, payload: dict) -> List[str]:
    """Validate one bench payload dict; returns a list of violations
    (empty = clean)."""
    problems = []
    if not isinstance(payload.get("metric"), str) or not payload["metric"]:
        problems.append("missing/empty 'metric'")
    if "trace_artifact" in payload:
        problems.extend(_check_trace_artifact(payload["trace_artifact"]))
    if payload.get("error") is not None:
        # Honest failure record: named error, no fabricated value.
        if not isinstance(payload["error"], str) or not payload["error"]:
            problems.append("'error' must be a non-empty string")
        if payload.get("value") is not None:
            problems.append("error record must not carry a 'value'")
        return [f"{name}: {p}" for p in problems]
    if not isinstance(payload.get("value"), numbers.Number):
        problems.append(f"'value' must be a number, got "
                        f"{payload.get('value')!r}")
    if not isinstance(payload.get("unit"), str) or not payload["unit"]:
        problems.append("missing/empty 'unit'")
    platform = payload.get("platform")
    if not isinstance(platform, str) or not platform:
        problems.append("missing/empty 'platform'")
    elif platform != "tpu" and not any(
            isinstance(payload.get(k), (str, dict, bool))
            and payload.get(k) for k in SMOKE_HONESTY_KEYS):
        problems.append(
            f"off-TPU measurement (platform={platform!r}) carries none "
            f"of the smoke-honesty keys {SMOKE_HONESTY_KEYS}")
    if payload.get("metric") in AB_METRICS:
        label, required_arms = AB_METRICS[payload["metric"]]
        arms = payload.get("per_arm")
        missing = [a for a in required_arms
                   if not isinstance(arms, dict)
                   or not isinstance(arms.get(a), dict)]
        if missing:
            problems.append(
                f"{label} A/B artifact missing arm(s) {missing} in "
                "'per_arm' — an A/B claim needs both measurements")
    if payload.get("metric") == AUTOSCALE_METRIC:
        drill = payload.get("drill")
        missing = [k for k in AUTOSCALE_COUNTERS
                   if not isinstance(drill, dict)
                   or not isinstance(drill.get(k), numbers.Number)]
        if missing:
            problems.append(
                f"autoscale drill artifact missing counter(s) {missing} "
                "in 'drill' — the convergence claim needs its audit "
                "trail")
    if payload.get("metric") == RELIABILITY_METRIC:
        drill = payload.get("drill")
        missing = [k for k in RELIABILITY_COUNTERS
                   if not isinstance(drill, dict)
                   or not isinstance(drill.get(k), numbers.Number)]
        if missing:
            problems.append(
                f"reliability drill artifact missing counter(s) "
                f"{missing} in 'drill' — the exactly-once claim needs "
                "its audit trail")
    return [f"{name}: {p}" for p in problems]


def check_file(path: str) -> List[str]:
    """Validate one BENCH_*.json file (wrapper or direct payload)."""
    name = os.path.basename(path)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"{name}: unreadable JSON ({e})"]
    if not isinstance(doc, dict):
        return [f"{name}: top level must be a JSON object"]
    if "parsed" in doc and "rc" in doc:          # round-driver wrapper
        if doc["parsed"] is None:
            if doc.get("rc") in (0, "0"):
                return [f"{name}: wrapper with rc=0 but parsed=null "
                        "(a successful run must parse to a payload)"]
            return []                            # honest failed run
        if not isinstance(doc["parsed"], dict):
            return [f"{name}: 'parsed' must be an object or null"]
        return check_payload(f"{name}[parsed]", doc["parsed"])
    return check_payload(name, doc)


def main(root: str = ".", argv=None) -> int:
    paths = sorted(glob.glob(os.path.join(root, "BENCH_*.json")))
    if not paths:
        print(f"no BENCH_*.json under {os.path.abspath(root)}")
        return 1
    problems = []
    for path in paths:
        found = check_file(path)
        problems.extend(found)
        status = "FAIL" if found else "ok"
        print(f"{status:4s} {os.path.basename(path)}")
    for p in problems:
        print(f"  VIOLATION: {p}")
    print(f"{len(paths) - len(set(p.split(':')[0] for p in problems))}"
          f"/{len(paths)} artifacts clean")
    return 1 if problems else 0


if __name__ == "__main__":
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.exit(main(repo))
