#!/usr/bin/env python
"""Ad-hoc on-device profiling probes (run alone on the TPU host).

Sections:
* ``msda``     — one dense-token DeformableTransformerEncoderLayer
  (jnp vs pallas backend), per-op breakdown.
* ``headline`` — the bench.py headline forward at batch 24, per-op
  breakdown of one dispatch.
* ``gru``      — the round-6 fused SepConvGRU kernel A/B: the non-small
  headline forward with ``RAFT_GRU_PALLAS`` forced on then off.
* ``motion``   — the round-7 fused motion-encoder kernel A/B
  (``RAFT_MOTION_PALLAS`` forced on then off), with an op-group MFU
  summary splitting the scan body into motion-encoder / GRU / custom-
  call slices so the two kernels' shares are separable per arm.
* ``step``     — the round-10 one-launch refine-iteration A/B across
  three arms (fused single kernel / chained motion+GRU kernels / pure
  XLA), with an op-group summary that collapses the whole scan body —
  the fused arm's win shows up as the step_pallas slice absorbing the
  motion_pallas + gru_pallas + update-conv slices of the chained arm.

Every breakdown now carries per-op achieved TFLOP/s + MFU when the
trace has ``flops`` stats (see ``raft_tpu/utils/profiling.py``), and a
program-level MFU from XLA's own cost model — so the next MFU wall is
nameable from this artifact alone, no TensorBoard round-trip.
"""

from __future__ import annotations

import contextlib
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from raft_tpu.utils import profiling
from raft_tpu.utils.envflags import forced_flag


def _program_flops(fn, *args):
    """Whole-dispatch FLOP count from XLA's cost model, when ``fn`` is a
    jitted callable (``.lower`` path); None otherwise / on any failure
    (cost_analysis shape varied across jax releases)."""
    if not hasattr(fn, "lower"):
        return None
    try:
        cost = fn.lower(*args).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        return float(cost.get("flops", 0.0)) or None
    except Exception:
        return None


def _run(fn, *args, groups=None):
    for _ in range(2):
        jnp.sum(fn(*args)).block_until_ready()
    flops = _program_flops(fn, *args)
    t0 = time.perf_counter()
    with profiling.trace() as t:
        out = fn(*args)
        float(jnp.sum(out))
    wall = time.perf_counter() - t0
    if flops:
        tf = flops / wall / 1e12
        line = (f"program: {flops / 1e12:.3f} TFLOP in {wall * 1e3:.1f} ms"
                f" wall -> {tf:.2f} TFLOP/s")
        peak = profiling.peak_tflops()
        if peak:
            line += f" = {100.0 * tf / peak:.1f}% MFU of {peak:g} peak"
        print(line)
    profiling.print_breakdown(t.logdir, steps=1, top=14)
    if groups:
        print("-- op groups --")
        profiling.op_group_summary(t.logdir, groups, steps=1)


def msda():
    from raft_tpu.models.deformable import (
        DeformableTransformerEncoder, DeformableTransformerEncoderLayer)

    h, w, d_model = 88, 120, 128
    tokens = h * w
    rng = jax.random.PRNGKey(0)
    src = jax.random.normal(rng, (1, tokens, d_model))
    ref = DeformableTransformerEncoder.get_reference_points([(h, w)])
    ref = jnp.broadcast_to(ref, (1, tokens, 1, 2))
    for backend in ("jnp", "pallas"):
        layer = DeformableTransformerEncoderLayer(
            d_model=d_model, d_ffn=d_model * 4, dropout=0.0,
            activation="gelu", n_levels=1, n_heads=8, n_points=4,
            backend=backend)
        variables = layer.init({"params": rng}, src, None, ref, [(h, w)])
        fwd = jax.jit(lambda s: layer.apply(variables, s, None, ref,
                                            [(h, w)]))
        print(f"=== msda_dense {tokens} tokens, backend={backend}")
        _run(fwd, src)


def headline():
    from raft_tpu.config import RAFTConfig
    from raft_tpu.models.raft import RAFT

    H, W = 440, 1024
    batch = int(os.environ.get("RAFT_PROBE_BATCH", "24"))
    # RAFT_PROBE_ALT=1 profiles the on-demand banded engine (the round-4
    # headline) instead of the materialized pyramid.
    alt = os.environ.get("RAFT_PROBE_ALT") == "1"
    cfg = RAFTConfig(iters=12, mixed_precision=True, alternate_corr=alt)
    model = RAFT(cfg)
    rng = jax.random.PRNGKey(0)
    img1 = jax.random.uniform(rng, (1, H, W, 3), jnp.float32) * 255.0
    variables = model.init({"params": rng, "dropout": rng}, img1, img1,
                           iters=1)
    img = jnp.broadcast_to(img1, (batch, H, W, 3))
    fwd = jax.jit(lambda a, b: model.apply(variables, a, b,
                                           test_mode=True)[1])
    print(f"=== headline {batch}x{H}x{W} iters=12 "
          f"engine={'alternate' if alt else 'all_pairs'}")
    _run(fwd, img, img)


def gru():
    """Round-6 tentpole A/B: per-op breakdown of the non-small headline
    forward with the fused SepConvGRU Pallas kernel forced on, then off.
    The flag is read at trace time, so each arm builds a fresh jit."""
    from raft_tpu.config import RAFTConfig
    from raft_tpu.models.raft import RAFT

    H, W = 440, 1024
    batch = int(os.environ.get("RAFT_PROBE_BATCH", "24"))
    cfg = RAFTConfig(iters=12, mixed_precision=True)
    model = RAFT(cfg)
    rng = jax.random.PRNGKey(0)
    img1 = jax.random.uniform(rng, (1, H, W, 3), jnp.float32) * 255.0
    variables = model.init({"params": rng, "dropout": rng}, img1, img1,
                           iters=1)
    img = jnp.broadcast_to(img1, (batch, H, W, 3))
    for label, flag in (("pallas", "1"), ("xla", "0")):
        with forced_flag("RAFT_GRU_PALLAS", flag):
            fwd = jax.jit(lambda a, b: model.apply(variables, a, b,
                                                   test_mode=True)[1])
            print(f"=== gru {batch}x{H}x{W} iters=12 gru={label}")
            _run(fwd, img, img)


# Op-name substring patterns splitting the scan body into the two fused-
# kernel subsystems (first match wins — custom calls before conv names,
# since a Pallas op's HLO name carries the kernel function's name).
_MOTION_GROUPS = {
    "motion_pallas": ("_motion_kernel", "motion_pallas"),
    "gru_pallas": ("_gru_kernel", "gru_pallas"),
    "motion_convs": ("convc1", "convc2", "convf1", "convf2",
                     "encoder/conv", "BasicMotionEncoder"),
    "gru_convs": ("convz", "convr", "convq"),
}


def motion():
    """Round-7 tentpole A/B: per-op breakdown + motion/GRU op-group MFU
    summary of the non-small headline forward with the fused motion-
    encoder kernel forced on, then off. Both arms force the fused GRU on
    (its round-6 win is established), so the delta isolates the motion
    chain. Flags are read at trace time — each arm builds a fresh jit."""
    from raft_tpu.config import RAFTConfig
    from raft_tpu.models.raft import RAFT

    H, W = 440, 1024
    batch = int(os.environ.get("RAFT_PROBE_BATCH", "24"))
    cfg = RAFTConfig(iters=12, mixed_precision=True)
    model = RAFT(cfg)
    rng = jax.random.PRNGKey(0)
    img1 = jax.random.uniform(rng, (1, H, W, 3), jnp.float32) * 255.0
    variables = model.init({"params": rng, "dropout": rng}, img1, img1,
                           iters=1)
    img = jnp.broadcast_to(img1, (batch, H, W, 3))
    for label, flag in (("pallas", "1"), ("xla", "0")):
        with forced_flag("RAFT_MOTION_PALLAS", flag), \
                forced_flag("RAFT_GRU_PALLAS", "1"):
            fwd = jax.jit(lambda a, b: model.apply(variables, a, b,
                                                   test_mode=True)[1])
            print(f"=== motion {batch}x{H}x{W} iters=12 motion={label}")
            _run(fwd, img, img, groups=_MOTION_GROUPS)


# Scan-body collapse for the step A/B: the fused kernel first (its HLO
# name carries _step_kernel), then the component kernels it subsumes,
# then the XLA conv names of the unfused update block (first match
# wins, so the fused arm's single custom call never double-counts).
_STEP_GROUPS = {
    "step_pallas": ("_step_kernel", "step_pallas"),
    "motion_pallas": ("_motion_kernel", "motion_pallas"),
    "gru_pallas": ("_gru_kernel", "gru_pallas"),
    "update_convs": ("convc1", "convc2", "convf1", "convf2",
                     "convz", "convr", "convq", "flow_head",
                     "BasicMotionEncoder"),
}


def step():
    """Round-10 tentpole A/B: per-op breakdown + scan-body op-group
    summary of the non-small headline forward under the three step
    dispatches — fused one-launch kernel, chained motion+GRU kernels,
    pure XLA (the same arms as ``bench.py --step``). Flags are read at
    trace time — each arm builds a fresh jit."""
    from raft_tpu.config import RAFTConfig
    from raft_tpu.models.raft import RAFT

    H, W = 440, 1024
    batch = int(os.environ.get("RAFT_PROBE_BATCH", "24"))
    cfg = RAFTConfig(iters=12, mixed_precision=True)
    model = RAFT(cfg)
    rng = jax.random.PRNGKey(0)
    img1 = jax.random.uniform(rng, (1, H, W, 3), jnp.float32) * 255.0
    variables = model.init({"params": rng, "dropout": rng}, img1, img1,
                           iters=1)
    img = jnp.broadcast_to(img1, (batch, H, W, 3))
    arms = (("fused", {"RAFT_STEP_PALLAS": "1"}),
            ("chained", {"RAFT_STEP_PALLAS": "0",
                         "RAFT_MOTION_PALLAS": "1",
                         "RAFT_GRU_PALLAS": "1"}),
            ("xla", {"RAFT_STEP_PALLAS": "0",
                     "RAFT_MOTION_PALLAS": "0",
                     "RAFT_GRU_PALLAS": "0"}))
    for label, env in arms:
        with contextlib.ExitStack() as stack:
            for flag, val in env.items():
                stack.enter_context(forced_flag(flag, val))
            fwd = jax.jit(lambda a, b: model.apply(variables, a, b,
                                                   test_mode=True)[1])
            print(f"=== step {batch}x{H}x{W} iters=12 step={label}")
            _run(fwd, img, img, groups=_STEP_GROUPS)


def sparse_b8():
    """VERDICT r2 #6: sparse_train b4->b8 doubles step time with flat
    samples/s and non-monotonic peak HBM. Per-op breakdown of one train
    step at both batches to name the op that doubles."""
    from raft_tpu.config import OursConfig, TrainConfig
    from raft_tpu.models import SparseRAFT
    from raft_tpu.parallel import create_train_state, make_train_step

    H, W = 352, 480
    rng = jax.random.PRNGKey(0)
    # RAFT_PROBE_SPARSE_ALT=1 profiles the on-demand (alternate_corr)
    # path — for the b4 anomaly (alt slower at b4 than b8, round 4).
    alt = os.environ.get("RAFT_PROBE_SPARSE_ALT") == "1"
    for batch in (4, 8):
        tcfg = TrainConfig(batch_size=batch, image_size=(H, W),
                           model_family="sparse", iters=6,
                           sparse_lambda=0.1)
        model = SparseRAFT(OursConfig(mixed_precision=True,
                                      alternate_corr=alt))
        state = create_train_state(rng, model, tcfg, (H, W))
        step_fn = make_train_step(tcfg, donate=False)
        b = {"image1": jnp.ones((batch, H, W, 3)) * 127.0,
             "image2": jnp.ones((batch, H, W, 3)) * 127.0,
             "flow": jnp.zeros((batch, H, W, 2)),
             "valid": jnp.ones((batch, H, W))}
        print(f"=== sparse_train step b{batch} {H}x{W}")
        _run(lambda s: step_fn(s, b, rng)[1]["loss"], state)


if __name__ == "__main__":
    names = sys.argv[1:] or ["msda", "headline"]
    print("devices:", jax.devices(), flush=True)
    for n in names:
        {"msda": msda, "headline": headline, "gru": gru,
         "motion": motion, "step": step, "sparse_b8": sparse_b8}[n]()
