#!/usr/bin/env python
"""Ad-hoc on-device profiling probes (run alone on the TPU host).

Sections:
* ``msda``     — one dense-token DeformableTransformerEncoderLayer
  (jnp vs pallas backend), per-op breakdown.
* ``headline`` — the bench.py headline forward at batch 24, per-op
  breakdown of one dispatch.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from raft_tpu.utils import profiling


def _run(fn, *args):
    for _ in range(2):
        jnp.sum(fn(*args)).block_until_ready()
    with profiling.trace() as t:
        out = fn(*args)
        float(jnp.sum(out))
    profiling.print_breakdown(t.logdir, steps=1, top=14)


def msda():
    from raft_tpu.models.deformable import (
        DeformableTransformerEncoder, DeformableTransformerEncoderLayer)

    h, w, d_model = 88, 120, 128
    tokens = h * w
    rng = jax.random.PRNGKey(0)
    src = jax.random.normal(rng, (1, tokens, d_model))
    ref = DeformableTransformerEncoder.get_reference_points([(h, w)])
    ref = jnp.broadcast_to(ref, (1, tokens, 1, 2))
    for backend in ("jnp", "pallas"):
        layer = DeformableTransformerEncoderLayer(
            d_model=d_model, d_ffn=d_model * 4, dropout=0.0,
            activation="gelu", n_levels=1, n_heads=8, n_points=4,
            backend=backend)
        variables = layer.init({"params": rng}, src, None, ref, [(h, w)])
        fwd = jax.jit(lambda s: layer.apply(variables, s, None, ref,
                                            [(h, w)]))
        print(f"=== msda_dense {tokens} tokens, backend={backend}")
        _run(fwd, src)


def headline():
    from raft_tpu.config import RAFTConfig
    from raft_tpu.models.raft import RAFT

    H, W = 440, 1024
    batch = int(os.environ.get("RAFT_PROBE_BATCH", "24"))
    cfg = RAFTConfig(iters=12, mixed_precision=True)
    model = RAFT(cfg)
    rng = jax.random.PRNGKey(0)
    img1 = jax.random.uniform(rng, (1, H, W, 3), jnp.float32) * 255.0
    variables = model.init({"params": rng, "dropout": rng}, img1, img1,
                           iters=1)
    img = jnp.broadcast_to(img1, (batch, H, W, 3))
    fwd = jax.jit(lambda a, b: model.apply(variables, a, b,
                                           test_mode=True)[1])
    print(f"=== headline {batch}x{H}x{W} iters=12")
    _run(fwd, img, img)


if __name__ == "__main__":
    names = sys.argv[1:] or ["msda", "headline"]
    print("devices:", jax.devices(), flush=True)
    for n in names:
        {"msda": msda, "headline": headline}[n]()
